"""kubectl-apply analog: load k8s-shaped YAML manifests into the sim API.

Parses the same manifest shapes the reference ships under
demo/specs/quickstart (Pods + ResourceClaims/Templates with DRA device
requests, plus the ComputeDomain CRD) so the demo specs are real YAML a
user could port to a live cluster, not test fixtures.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import yaml

from k8s_dra_driver_tpu.api.computedomain import (
    ComputeDomain,
    ComputeDomainChannelSpec,
    ComputeDomainSpec,
)
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import (
    Container,
    Pod,
    PodResourceClaimRef,
    ResourceClaim,
    ResourceClaimTemplate,
)
from k8s_dra_driver_tpu.k8s.manifest import (
    device_configs_from_spec as _device_configs,
    device_requests_from_spec as _device_requests,
    unwrap_template_spec,
)
from k8s_dra_driver_tpu.k8s.objects import K8sObject, new_meta


class ManifestError(ValueError):
    pass


def _meta(doc: Dict[str, Any]):
    md = doc.get("metadata", {})
    if "name" not in md:
        raise ManifestError(f"manifest {doc.get('kind')} missing metadata.name")
    return new_meta(md["name"], md.get("namespace", "default"),
                    labels=md.get("labels", {}))


def _pod(doc: Dict[str, Any]) -> Pod:
    spec = doc.get("spec", {})
    containers = [
        Container(
            name=c.get("name", "main"),
            image=c.get("image", ""),
            command=c.get("command", []),
            env={e["name"]: str(e.get("value", "")) for e in c.get("env", [])},
        )
        for c in spec.get("containers", [])
    ]
    claims = [
        PodResourceClaimRef(
            name=rc.get("name", "claim"),
            resource_claim_name=rc.get("resourceClaimName", ""),
            resource_claim_template_name=rc.get("resourceClaimTemplateName", ""),
        )
        for rc in spec.get("resourceClaims", [])
    ]
    return Pod(meta=_meta(doc), containers=containers, resource_claims=claims,
               node_name=spec.get("nodeName", ""),
               priority_tier=int(spec.get("priorityTier", 0)))


def _claim(doc: Dict[str, Any]) -> ResourceClaim:
    spec = doc.get("spec", {})
    return ResourceClaim(
        meta=_meta(doc),
        requests=_device_requests(spec),
        config=_device_configs(spec),
        priority_tier=int(spec.get("priorityTier", 0)),
    )


def _claim_template(doc: Dict[str, Any]) -> ResourceClaimTemplate:
    spec = unwrap_template_spec(doc.get("spec", {}))
    return ResourceClaimTemplate(
        meta=_meta(doc),
        requests=_device_requests(spec),
        config=_device_configs(spec),
    )


def _job(doc: Dict[str, Any]) -> List[Pod]:
    """Expand a batch/v1 Indexed Job into its worker pods — the sim's job
    controller, collapsed into apply time. Pods are named <job>-<index>
    and get JOB_COMPLETION_INDEX, matching what a real indexed Job's pods
    see (the reference ships its allreduce proof as an MPIJob,
    /root/reference/demo/specs/imex/nvbandwidth-test-job.yaml; the TPU
    analog uses an Indexed Job since jax.distributed needs no launcher)."""
    spec = doc.get("spec", {})
    if spec.get("completionMode", "Indexed") != "Indexed":
        raise ManifestError("sim supports completionMode: Indexed jobs only")
    completions = int(spec.get("completions", spec.get("parallelism", 1)))
    template = dict(spec.get("template", {}))
    md = doc.get("metadata", {})
    pods: List[Pod] = []
    for idx in range(completions):
        pod_doc = {
            "kind": "Pod",
            "metadata": {
                "name": f"{md.get('name', 'job')}-{idx}",
                "namespace": md.get("namespace", "default"),
                "labels": {
                    **template.get("metadata", {}).get("labels", {}),
                    "batch.kubernetes.io/job-name": md.get("name", "job"),
                    "batch.kubernetes.io/job-completion-index": str(idx),
                },
            },
            "spec": template.get("spec", {}),
        }
        pod = _pod(pod_doc)
        for c in pod.containers:
            c.env.setdefault("JOB_COMPLETION_INDEX", str(idx))
        pods.append(pod)
    return pods


def _compute_domain(doc: Dict[str, Any]) -> ComputeDomain:
    spec = doc.get("spec", {})
    channel = spec.get("channel", {}) or {}
    rct = channel.get("resourceClaimTemplate", {}) or {}
    return ComputeDomain(
        meta=_meta(doc),
        spec=ComputeDomainSpec(
            num_nodes=spec.get("numNodes", 0),
            topology=spec.get("topology", ""),
            channel=ComputeDomainChannelSpec(
                resource_claim_template_name=rct.get("name", ""),
            ),
        ),
    )


def _serving_group(doc: Dict[str, Any]):
    """ServingGroup manifests reuse the real k8s wire decoder (the YAML
    keys ARE the wire keys); only namespace defaulting is kubectl's."""
    from k8s_dra_driver_tpu.k8s.k8swire import from_k8s_wire

    obj = from_k8s_wire({**doc, "kind": "ServingGroup"})
    obj.meta = _meta(doc)
    return obj


def _tenant_quota(doc: Dict[str, Any]):
    """TenantQuota manifests go through the real k8s wire decoder too."""
    from k8s_dra_driver_tpu.k8s.k8swire import from_k8s_wire

    obj = from_k8s_wire({**doc, "kind": "TenantQuota"})
    obj.meta = _meta(doc)
    return obj


_KIND_BUILDERS = {
    "Pod": _pod,
    "ResourceClaim": _claim,
    "ResourceClaimTemplate": _claim_template,
    "ComputeDomain": _compute_domain,
    "ServingGroup": _serving_group,
    "TenantQuota": _tenant_quota,
    "Job": _job,
}


def load_manifests(text: str) -> List[K8sObject]:
    objs: List[K8sObject] = []
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        kind = doc.get("kind", "")
        if kind == "Namespace":
            continue  # namespaces are implicit in the fake API
        builder = _KIND_BUILDERS.get(kind)
        if builder is None:
            raise ManifestError(f"unsupported manifest kind {kind!r}")
        built = builder(doc)
        objs.extend(built if isinstance(built, list) else [built])
    return objs


def apply_file(api: APIServer, path: str) -> List[K8sObject]:
    with open(path, "r", encoding="utf-8") as f:
        objs = load_manifests(f.read())
    created = []
    for obj in objs:
        created.append(api.create(obj))
    return created


# -- CLI ---------------------------------------------------------------------
#
# kubectl-style operator CLI against a tpu-dra-apiserver / sim cluster:
#
#   tpu-kubectl --server http://127.0.0.1:8001 apply -f pod.yaml
#   tpu-kubectl get pods -n default [-o json]
#   tpu-kubectl delete pod my-pod -n default
#   tpu-kubectl wait pod my-pod -n default --for=Running --timeout=30
#
# The server defaults to $TPU_KUBECTL_SERVER. Kind aliases follow kubectl
# conventions (pods/po, resourceclaims/rc, computedomains/cd, ...).

_KIND_ALIASES = {
    "pod": "Pod", "pods": "Pod", "po": "Pod",
    "node": "Node", "nodes": "Node",
    "event": "Event", "events": "Event", "ev": "Event",
    "resourceclaim": "ResourceClaim", "resourceclaims": "ResourceClaim",
    "claim": "ResourceClaim", "claims": "ResourceClaim",
    "resourceclaimtemplate": "ResourceClaimTemplate",
    "resourceclaimtemplates": "ResourceClaimTemplate",
    "resourceslice": "ResourceSlice", "resourceslices": "ResourceSlice",
    "deviceclass": "DeviceClass", "deviceclasses": "DeviceClass",
    "daemonset": "DaemonSet", "daemonsets": "DaemonSet", "ds": "DaemonSet",
    "computedomain": "ComputeDomain", "computedomains": "ComputeDomain",
    "cd": "ComputeDomain",
    "computedomainclique": "ComputeDomainClique",
    "computedomaincliques": "ComputeDomainClique",
    "servinggroup": "ServingGroup", "servinggroups": "ServingGroup",
    "sg": "ServingGroup",
    "tenantquota": "TenantQuota", "tenantquotas": "TenantQuota",
    "tq": "TenantQuota",
}


def _resolve_kind(token: str) -> str:
    kind = _KIND_ALIASES.get(token.lower())
    if kind is None:
        raise SystemExit(f"error: unknown resource kind {token!r}")
    return kind


def _cluster_map() -> Dict[str, str]:
    """The TPU_KUBECTL_CLUSTERS env ("name=url,name2=url2" — the
    kubeconfig analog) parsed into {name: base_url}. Empty when unset —
    fan-out commands turn that into a hard error so a typo'd env var
    never silently narrows the fleet to nothing."""
    import os

    clusters: Dict[str, str] = {}
    for entry in os.environ.get("TPU_KUBECTL_CLUSTERS", "").split(","):
        if "=" in entry:
            name, _, url = entry.partition("=")
            clusters[name.strip()] = url.strip()
    return clusters


def _resolve_cluster(token: str) -> str:
    """``--cluster`` accepts a URL directly or a name defined in
    TPU_KUBECTL_CLUSTERS, so `get`/`top`/`explain` run against leader or
    follower identically by switching one flag."""
    if token.startswith(("http://", "https://")):
        return token
    clusters = _cluster_map()
    url = clusters.get(token)
    if url is None:
        known = ", ".join(sorted(clusters)) or "none defined"
        raise SystemExit(f"error: unknown cluster {token!r} "
                         f"(TPU_KUBECTL_CLUSTERS: {known})")
    return url


_CLUSTER_SCOPED = {"Node", "DeviceClass", "ResourceSlice"}


def _default_namespace(kind: str, namespace: str) -> str:
    """kubectl semantics: an omitted -n means the 'default' namespace for
    namespaced kinds, and no namespace at all for cluster-scoped ones."""
    if namespace:
        return namespace
    return "" if kind in _CLUSTER_SCOPED else "default"


def _summary_row(obj: K8sObject) -> List[str]:
    extra = ""
    if obj.kind == "Pod":
        extra = getattr(obj, "phase", "")
        if getattr(obj, "ready", False):
            extra += " (ready)"
    elif obj.kind == "ComputeDomain":
        extra = getattr(getattr(obj, "status", None), "status", "")
    elif obj.kind == "ResourceClaim":
        alloc = getattr(obj, "allocation", None)
        extra = "allocated" if alloc and alloc.devices else "pending"
    elif obj.kind == "ResourceSlice":
        extra = f"{len(getattr(obj, 'devices', []))} devices"
    elif obj.kind == "Event":
        extra = (f"{getattr(obj, 'type', '')}/{getattr(obj, 'reason', '')} "
                 f"x{getattr(obj, 'count', 1)}")
    elif obj.kind == "ServingGroup":
        st = getattr(obj, "status", None)
        ready = getattr(st, "ready_replicas", 0)
        extra = (f"{ready}/{obj.spec.replicas} ready"
                 + (f" @{obj.spec.profile}" if obj.spec.profile else ""))
    elif obj.kind == "TenantQuota":
        quota = (str(obj.spec.chip_quota) if obj.spec.chip_quota
                 else "unlimited")
        extra = (f"weight={obj.spec.weight:g} "
                 f"chips={obj.status.chips_used}/{quota}"
                 + (f" tier>={obj.spec.priority_floor}"
                    if obj.spec.priority_floor else ""))
    return [obj.namespace or "-", obj.meta.name, extra]


# -- describe ----------------------------------------------------------------


def _age(ts: float, now: float) -> str:
    if not ts:
        return "<unknown>"
    s = max(0, int(now - ts))
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    return f"{s // 3600}h"


def _table(rows: List[List[str]], indent: str = "  ") -> List[str]:
    if not rows:
        return []
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return [
        indent + "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        for r in rows
    ]


def _pct(v: float) -> str:
    return f"{100.0 * v:.0f}%"


def _gib(b: float) -> str:
    return f"{b / 2**30:.1f}Gi"


def _utilization_lines(u) -> List[str]:
    """The UTILIZATION section `describe` renders for claims/domains
    carrying a telemetry summary."""
    if u is None:
        return []
    lines = [
        "Utilization:",
        f"  Duty p95:  {_pct(u.duty_cycle_p95)} over "
        f"{u.window_seconds:.0f}s window ({u.samples} samples)",
        f"  HBM p95:   {_gib(u.hbm_used_p95_bytes)} / "
        f"{_gib(u.hbm_total_bytes)}",
    ]
    if u.ici_utilization_p95 > 0:
        lines.append(f"  ICI p95:   {_pct(u.ici_utilization_p95)}")
    return lines


def _conditions_lines(conditions, now: float) -> List[str]:
    if not conditions:
        return []
    rows = [["Type", "Status", "Reason", "Age", "Message"]]
    for c in conditions:
        rows.append([
            getattr(c, "type", ""),
            getattr(c, "status", ""),
            getattr(c, "reason", "") or "-",
            _age(getattr(c, "last_transition_time", 0.0), now),
            getattr(c, "message", "") or "-",
        ])
    return ["Conditions:"] + _table(rows)


def _events_lines(api, obj: K8sObject, now: float) -> List[str]:
    from k8s_dra_driver_tpu.pkg.events import events_for

    events = events_for(api, obj)
    if not events:
        return ["Events:  <none>"]
    rows = [["Type", "Reason", "Age", "Count", "From", "Message"]]
    for ev in events:
        first = _age(ev.first_timestamp, now)
        last = _age(ev.last_timestamp, now)
        age = last if ev.count <= 1 else f"{last} (first {first})"
        rows.append([ev.type, ev.reason, age, str(ev.count),
                     ev.source or "-", ev.message])
    return ["Events:"] + _table(rows)


def _describe_body(api, obj: K8sObject) -> List[str]:
    lines: List[str] = []
    if obj.kind == "Pod":
        lines += [f"Node:   {obj.node_name or '<none>'}",
                  f"Phase:  {obj.phase}" + (" (ready)" if obj.ready else ""),
                  f"IP:     {obj.pod_ip or '<none>'}"]
        for ref in obj.resource_claims:
            src = ref.resource_claim_name or f"template/{ref.resource_claim_template_name}"
            lines.append(f"Claim:  {ref.name} -> {src}")
        if obj.injected_devices:
            lines.append("Devices: " + ",".join(obj.injected_devices))
        lines += _conditions_lines(obj.conditions, time.time())
    elif obj.kind == "ResourceClaim":
        for req in obj.requests:
            lines.append(
                f"Request: {req.name or '-'} class={req.device_class_name} "
                f"mode={req.allocation_mode} count={req.count}")
        alloc = obj.allocation
        if alloc is not None and alloc.devices:
            lines.append(f"Allocated on: {alloc.node_name or '<none>'}")
            for d in alloc.devices:
                lines.append(f"  {d.driver}: {d.device} (request {d.request})")
        else:
            lines.append("Allocated on: <pending>")
        for r in obj.reserved_for:
            lines.append(f"Reserved for: {r.kind}/{r.name}")
        lines += _utilization_lines(obj.utilization)
        lines += _conditions_lines(obj.conditions, time.time())
    elif obj.kind == "ComputeDomain":
        lines += [f"NumNodes:  {obj.spec.num_nodes}",
                  f"Topology:  {obj.spec.topology or '<any>'}",
                  f"Status:    {obj.status.status}"]
        # Elastic membership: epoch counter, the current epoch's
        # membership target when it diverges from spec (a healed domain),
        # and the in-flight resize record.
        if obj.status.epoch or obj.status.desired_nodes or obj.status.resize:
            desired = obj.status.desired_nodes or obj.spec.num_nodes
            lines.append(
                f"Epoch:     {obj.status.epoch} "
                f"(membership {desired}/{obj.spec.num_nodes} desired)")
        if obj.status.resize is not None:
            r = obj.status.resize
            lines.append(
                f"Resizing:  {r.phase} ({r.trigger}) -> {r.target_nodes} "
                f"host(s), attempt {r.attempts}"
                + (f", lost: {','.join(r.lost_nodes)}" if r.lost_nodes
                   else ""))
        if obj.status.placement is not None:
            p = obj.status.placement
            lines.append(
                f"Placement: block {p.block_shape}@{p.block_origin} of "
                f"{p.ici_domain or '<default>'} "
                f"({','.join(p.nodes)})")
        if obj.status.mesh_bundle is not None:
            mb = obj.status.mesh_bundle
            axes = ",".join(f"{n}={s}" for n, s in
                            zip(mb.axis_names, mb.axis_sizes))
            lines.append(
                f"MeshBundle: rev {mb.revision} axes ({axes}) "
                f"grid {mb.slice_topology} hops {mb.hop_score} "
                f"(naive {mb.naive_hop_score})"
                + (f" routed around {len(mb.broken_links)} dead link(s)"
                   if mb.broken_links else ""))
            # Flat device order as worker:chip tokens — the permutation a
            # claiming pod applies to jax.devices(); truncated so a big
            # slice doesn't flood the terminal.
            toks = [f"{d.worker}:{d.chip}" for d in mb.device_order]
            shown, extra = toks[:32], len(toks) - 32
            lines.append("  Order: " + " ".join(shown)
                         + (f" ...(+{extra})" if extra > 0 else ""))
        if obj.status.nodes:
            rows = [["Node", "IciDomain", "Worker", "Status"]]
            for n in obj.status.nodes:
                rows.append([n.name, n.ici_domain, str(n.worker_id), n.status])
            lines += ["Nodes:"] + _table(rows)
        lines += _utilization_lines(obj.status.utilization)
        lines += _conditions_lines(obj.status.conditions, time.time())
    elif obj.kind == "ServingGroup":
        s, st = obj.spec, obj.status
        lines += [
            f"Replicas:  {st.ready_replicas} ready / {s.replicas} desired"
            + (f" (demand {st.desired_replicas})"
               if st.desired_replicas != s.replicas else ""),
            f"Profile:   {s.profile or '<single chip>'}"
            + (f" (tiers: {', '.join(t or '<single chip>' for t in s.tiers)})"
               if s.tiers else ""),
            f"SLO:       latency p95 <= {s.slo.latency_p95_ms:g}ms, "
            f"duty <= {s.slo.duty_bound:g}",
            f"Traffic:   {s.traffic.trace or '<none>'} "
            f"(peak {s.traffic.peak_qps:g} qps, "
            f"{s.traffic.qps_per_chip:g} qps/chip)",
        ]
        if st.traffic is not None:
            t = st.traffic
            lines.append(
                f"Observed:  {t.qps:g} qps, latency {t.latency_ms:g}ms "
                f"({t.latency_ratio:.2f}x bound), "
                f"utilization {_pct(t.utilization)}")
        scale_notes = []
        if st.last_scale_up:
            scale_notes.append(f"up @{st.last_scale_up:g}s")
        if st.last_scale_down:
            scale_notes.append(f"down @{st.last_scale_down:g}s")
        if st.last_retier:
            scale_notes.append(f"retier @{st.last_retier:g}s")
        if scale_notes:
            lines.append("LastScale: " + ", ".join(scale_notes)
                         + " (virtual clock)")
        lines += _conditions_lines(st.conditions, time.time())
    elif obj.kind == "TenantQuota":
        s, st = obj.spec, obj.status
        lines += [
            f"Weight:       {s.weight:g} (WFQ share)",
            f"ChipQuota:    {s.chip_quota if s.chip_quota else '<unlimited>'}",
            f"PriorityFloor: {s.priority_floor}",
            f"ChipsUsed:    {st.chips_used}",
            f"Pending:      {st.pods_pending} pod(s)",
            f"VirtualTime:  {st.virtual_time:g}",
        ]
    elif obj.kind == "Node":
        from k8s_dra_driver_tpu.rebalancer.controller import (
            DRAIN_READY_ANNOTATION,
        )

        if obj.meta.annotations.get(DRAIN_READY_ANNOTATION):
            lines.append("Drain-ready: true (rebalancer: zero allocated "
                         "chips — host is reclaimable)")
        for t in getattr(obj, "taints", []):
            lines.append(f"Taint: {t.key}={t.value}:{t.effect}")
        slices = [s for s in api.list("ResourceSlice")
                  if s.node_name == obj.meta.name]
        for s in slices:
            tainted = [d.name for d in s.devices if d.taints]
            lines.append(
                f"ResourceSlice: {s.meta.name} driver={s.driver} "
                f"devices={len(s.devices)}"
                + (f" tainted=[{','.join(tainted)}]" if tainted else ""))
    return lines


# -- top ---------------------------------------------------------------------
#
# `tpu-kubectl top nodes|claims|computedomains`: sorted utilization tables.
# Claims and domains read their utilizationSummary straight off status;
# nodes aggregate the per-chip gauges from a /metrics scrape (the sim's
# --metrics-port, or any node's MetricsServer).


def _history_cols(history, series: str) -> List[str]:
    """[MEAN-1M, P95-1M] off the flight recorder's one-minute tier: the
    mean of the retained bucket means and the worst retained bucket p95
    — hours of lookback where the status summary holds one window."""
    pts = history.query(series, resolution="1m") if history is not None else []
    if not pts:
        return ["-", "-"]
    mean = sum(p["mean"] for p in pts) / len(pts)
    return [_pct(mean), _pct(max(p["p95"] for p in pts))]


def top_claim_rows(objs: List[K8sObject], history=None) -> List[List[str]]:
    rows = [["NAMESPACE", "NAME", "DUTY-P95", "HBM-P95", "HBM-TOTAL",
             "WINDOW", "SAMPLES"]]
    if history is not None:
        rows[0] += ["MEAN-1M", "P95-1M"]
    ranked = sorted(
        (o for o in objs if getattr(o, "utilization", None) is not None),
        key=lambda o: -o.utilization.duty_cycle_p95)
    for o in ranked:
        u = o.utilization
        row = [o.namespace or "-", o.meta.name, _pct(u.duty_cycle_p95),
               _gib(u.hbm_used_p95_bytes), _gib(u.hbm_total_bytes),
               f"{u.window_seconds:.0f}s", str(u.samples)]
        if history is not None:
            row += _history_cols(
                history, f"claim-duty/{o.namespace}/{o.meta.name}")
        rows.append(row)
    return rows


def top_domain_rows(objs: List[K8sObject], history=None) -> List[List[str]]:
    rows = [["NAMESPACE", "NAME", "DUTY-P95", "HBM-P95", "ICI-P95",
             "WINDOW", "SAMPLES"]]
    if history is not None:
        rows[0] += ["ICI-MEAN-1M", "ICI-P95-1M"]
    ranked = sorted(
        (o for o in objs if o.status.utilization is not None),
        key=lambda o: -o.status.utilization.duty_cycle_p95)
    for o in ranked:
        u = o.status.utilization
        row = [o.namespace or "-", o.meta.name, _pct(u.duty_cycle_p95),
               _gib(u.hbm_used_p95_bytes), _pct(u.ici_utilization_p95),
               f"{u.window_seconds:.0f}s", str(u.samples)]
        if history is not None:
            row += _history_cols(
                history, f"domain-ici/{o.namespace}/{o.meta.name}")
        rows.append(row)
    return rows


def top_servinggroup_rows(objs: List[K8sObject]) -> List[List[str]]:
    """`top servinggroups`: ranked by latency pressure (ratio of the
    declared bound), the row an operator scans when pages fire."""
    rows = [["NAMESPACE", "NAME", "READY", "REPLICAS", "PROFILE", "QPS",
             "UTIL", "LAT-RATIO"]]
    with_traffic = [o for o in objs
                    if getattr(o.status, "traffic", None) is not None]
    ranked = sorted(with_traffic,
                    key=lambda o: -o.status.traffic.latency_ratio)
    for o in ranked:
        t = o.status.traffic
        rows.append([
            o.namespace or "-", o.meta.name,
            str(o.status.ready_replicas), str(o.spec.replicas),
            o.spec.profile or "chip", f"{t.qps:g}",
            _pct(t.utilization), f"{t.latency_ratio:.2f}",
        ])
    return rows


def top_node_rows(metrics_text: str) -> List[List[str]]:
    """Aggregate the per-chip telemetry gauges of one scrape into a
    per-node table (one scrape of the sim's shared registry covers the
    whole fleet — every node plugin exposes on it)."""
    from k8s_dra_driver_tpu.pkg.telemetry import parse_metrics_text

    samples = parse_metrics_text(metrics_text)

    def by_node(metric: str) -> Dict[str, List[float]]:
        out: Dict[str, List[float]] = {}
        for labels, value in samples.get(metric, {}).items():
            node = dict(labels).get("node", "")
            if node:
                out.setdefault(node, []).append(value)
        return out

    duty = by_node("tpu_dra_chip_duty_cycle")
    hbm = by_node("tpu_dra_chip_hbm_used_bytes")
    power = by_node("tpu_dra_chip_power_watts")
    errors = by_node("tpu_dra_ici_link_errors_total")
    rows = [["NODE", "CHIPS", "DUTY", "HBM-USED", "POWER", "ICI-ERRS"]]
    ranked = sorted(duty, key=lambda n: -(sum(duty[n]) / len(duty[n])))
    for node in ranked:
        d = duty[node]
        rows.append([
            node, str(len(d)), _pct(sum(d) / len(d)),
            _gib(sum(hbm.get(node, []))),
            f"{sum(power.get(node, [])):.0f}W",
            f"{sum(errors.get(node, [])):.0f}",
        ])
    return rows


def top_rows_all_clusters(clusters: Dict[str, str], kind: str,
                          namespace=None,
                          history: bool = False) -> List[List[str]]:
    """`top ... --all-clusters`: every federated cluster's utilization
    table under one header with a leading CLUSTER column. Nodes scrape
    each cluster's /metrics route; claims/domains/servinggroups list
    each cluster's store. Dark or capability-less peers degrade to
    SKIPPED rows."""
    from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer

    def one_cluster(capi) -> Optional[List[List[str]]]:
        """One peer's table, or None when it lacks the capability. One
        list per cluster — each iteration scans a DIFFERENT store."""
        if kind == "Node":
            text = capi.metrics_text()
            return None if text is None else top_node_rows(text)
        objs = capi.list(kind, namespace=namespace)
        hist = capi.history if history else None
        if kind == "ResourceClaim":
            return top_claim_rows(objs, history=hist)
        if kind == "ComputeDomain":
            return top_domain_rows(objs, history=hist)
        return top_servinggroup_rows(objs)

    out: List[List[str]] = []
    skipped: List[tuple] = []
    for cname in sorted(clusters):
        try:
            rows = one_cluster(RemoteAPIServer(clusters[cname]))
        except OSError as exc:
            skipped.append((cname, f"unreachable: {exc}"))
            continue
        if rows is None:
            skipped.append((cname, "no metrics registry attached"))
            continue
        if not out:
            out.append(["CLUSTER"] + rows[0])
        for row in rows[1:]:
            out.append([cname] + row)
    if not out:
        out = [["CLUSTER", "STATUS", "DETAIL"]]
    width = len(out[0])
    for cname, reason in skipped:
        row = [cname, "SKIPPED", reason]
        out.append(row[:width] + ["-"] * max(0, width - len(row)))
    return out


def _print_table(rows: List[List[str]]) -> None:
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


# -- explain -----------------------------------------------------------------
#
# `tpu-kubectl explain <kind> <name>`: the merged causal timeline of one
# object — deduplicated Events and flight-recorder DecisionRecords
# (pkg/history.py) in one wall-clock order, every row linking its trace id,
# plus a telemetry sparkline rendered off the recorder's downsampled tiers.
# Works against the in-process sim (`sim explain`) and over the wire
# (RemoteAPIServer.history -> /history routes) identically.


def _compact(v: Any, cap: int = 64) -> str:
    s = str(v)
    return s if len(s) <= cap else s[: cap - 3] + "..."


def _spark_series_for(api, obj: K8sObject) -> str:
    """The telemetry series explain charts for one object. A Pod has no
    series of its own — chart the claim reserved for it (its chips)."""
    if obj.kind == "Node":
        return f"node-duty/{obj.meta.name}"
    if obj.kind == "ResourceClaim":
        return f"claim-duty/{obj.namespace}/{obj.meta.name}"
    if obj.kind == "ComputeDomain":
        return f"domain-ici/{obj.namespace}/{obj.meta.name}"
    if obj.kind == "Pod":
        for c in sorted(api.list("ResourceClaim", namespace=obj.namespace),
                        key=lambda c: c.meta.name):
            if any(r.kind == "Pod" and r.name == obj.meta.name
                   for r in getattr(c, "reserved_for", [])):
                return f"claim-duty/{c.namespace}/{c.meta.name}"
    return ""


def explain_timeline_entries(api, obj, decisions,
                             now: float) -> List[tuple]:
    """``(wall, priority, [TIME, SOURCE, WHAT, TRACE])`` tuples, oldest
    first. Events and decisions both carry wall timestamps
    (DecisionRecord.wall exists for exactly this merge — its ``time``
    field is the caller's virtual clock, disjoint from Event
    timestamps). The wall key stays exposed so the cross-cluster merge
    can interleave several clusters' entries into one order; ``obj``
    may be None (decisions-only — e.g. the object lives on a peer)."""
    from k8s_dra_driver_tpu.pkg.events import events_for

    merged: List[tuple] = []
    if obj is not None:
        for ev in events_for(api, obj):
            what = f"{ev.type}/{ev.reason}"
            if ev.count > 1:
                what += f" x{ev.count}"
            merged.append((ev.last_timestamp, 0, [
                _age(ev.last_timestamp, now),
                f"event/{ev.source or '-'}",
                what + f": {ev.message}",
                getattr(ev, "trace_id", "") or "-",
            ]))
    for r in decisions:
        what = f"{r.rule} -> {r.outcome}: {r.message}"
        if r.inputs:
            what += (" [" + " ".join(f"{k}={_compact(v)}"
                                     for k, v in sorted(r.inputs.items()))
                     + "]")
        merged.append((r.wall, 1, [
            _age(r.wall, now), r.controller, what, r.trace_id or "-"]))
    merged.sort(key=lambda t: (t[0], t[1]))
    return merged


def explain_timeline_rows(api, obj: K8sObject, decisions,
                          now: float) -> List[List[str]]:
    """The merged TIME/SOURCE/WHAT/TRACE rows, oldest first."""
    return [row for _, _, row
            in explain_timeline_entries(api, obj, decisions, now)]


def lifecycle_breakdown_lines(api, kind: str, namespace: str,
                              name: str) -> List[str]:
    """`explain --latency`: the claim's critical-path phase breakdown.
    In-process the lifecycle analyzer's finished profile is
    authoritative; over the wire the same numbers ride the
    ``lifecycle/claim-profiled`` DecisionRecord's inputs (already served
    by /history/decisions), so remote explain needs no extra route.
    Empty when the claim has not been profiled (consumer not Running
    yet) or the kind is not a claim."""
    if kind != "ResourceClaim":
        return []
    from k8s_dra_driver_tpu.pkg.history import RULE_LIFECYCLE_PROFILE
    from k8s_dra_driver_tpu.pkg.lifecycle import ALL_PHASES

    phases: Dict[str, float] = {}
    total = None
    analyzer = getattr(api, "lifecycle", None)
    profile = (analyzer.breakdown(namespace, name)
               if analyzer is not None else None)
    if profile is not None:
        phases = dict(profile.phase_seconds)
        total = profile.total_seconds
    else:
        hist = getattr(api, "history", None)
        for r in (hist.decisions_for(kind, namespace, name)
                  if hist is not None else []):
            if r.rule == RULE_LIFECYCLE_PROFILE:
                phases = {k: float(v) for k, v in r.inputs.items()
                          if k != "total"}
                total = float(r.inputs.get("total", 0.0))
    if total is None:
        return []
    rows = [["PHASE", "SECONDS"]]
    for phase in ALL_PHASES:
        if phase in phases:
            rows.append([phase, f"{phases[phase]:.2f}"])
    for phase in sorted(set(phases) - set(ALL_PHASES)):
        rows.append([phase, f"{phases[phase]:.2f}"])
    rows.append(["total", f"{total:.2f}"])
    return ["Latency:"] + _table(rows)


def explain_object(api, kind: str, name: str, namespace: str = "",
                   latency: bool = False) -> str:
    """Render the `explain` view: identity, the merged Event+Decision
    causal timeline, and the telemetry sparkline. ``api`` needs only
    get/list plus an optional ``history`` attribute (the sim's
    HistoryStore, or RemoteAPIServer's /history adapter; None degrades
    to an events-only timeline). ``latency`` appends the critical-path
    phase breakdown for claims."""
    from k8s_dra_driver_tpu.pkg.history import sparkline

    obj = api.get(kind, name, namespace)
    now = time.time()
    hist = getattr(api, "history", None)
    decisions = (hist.decisions_for(kind, obj.namespace or "", obj.meta.name)
                 if hist is not None else [])
    # A workload stamped with a fleet-level trace context (a spilled or
    # globally-placed object) gets its timeline stitched: decisions
    # recorded against other objects under the same trace join in.
    from k8s_dra_driver_tpu.pkg import tracing
    ctx = tracing.extract_context(obj.meta.annotations)
    if hist is not None and ctx is not None:
        ids = {ctx.trace_id} | {r.trace_id for r in decisions if r.trace_id}
        seen = {(r.wall, r.controller, r.name, r.outcome) for r in decisions}
        try:
            extra = hist.decisions_by_trace(sorted(ids))
        except AttributeError:  # pre-stitching history surface
            extra = []
        decisions = decisions + [
            r for r in extra
            if (r.wall, r.controller, r.name, r.outcome) not in seen]
    lines = [f"Name:       {obj.meta.name}"]
    if obj.meta.namespace:
        lines.append(f"Namespace:  {obj.meta.namespace}")
    lines.append(f"Kind:       {obj.kind}")
    rows = explain_timeline_rows(api, obj, decisions, now)
    if rows:
        lines += ["Timeline:"] + _table(
            [["TIME", "SOURCE", "WHAT", "TRACE"]] + rows)
    else:
        lines.append("Timeline:   <none>")
    if latency:
        lat = lifecycle_breakdown_lines(
            api, kind, obj.namespace or "", obj.meta.name)
        lines += lat or ["Latency:    <not profiled — claim's consumer "
                         "has not reached Running>"]
    series = _spark_series_for(api, obj) if hist is not None else ""
    if series:
        pts = hist.query(series, resolution="1m")
        vals = [p["mean"] for p in pts]
        label = "1m tier"
        if not vals:
            vals = [p["value"] for p in hist.query(series)]
            label = "raw"
        if vals:
            lines.append(f"Telemetry:  {series} ({label}, "
                         f"{len(vals)} points)")
            lines.append(f"  {sparkline(vals)}  "
                         f"[{min(vals):.3f} .. {max(vals):.3f}]")
    if hist is None:
        lines.append("(no flight recorder attached: events only)")
    return "\n".join(lines)


def explain_all_clusters(clusters: Dict[str, str], kind: str, name: str,
                         namespace: str = "",
                         latency: bool = False) -> str:
    """`explain --all-clusters`: fan out over every federated cluster's
    /history + event surfaces and merge the per-cluster timelines into
    ONE wall-ordered causal view, each row stamped with the cluster it
    came from and that cluster's replication staleness. A peer that is
    unreachable or predates the flight recorder (404 "no history store
    attached") degrades to a loud SKIPPED row — the fleet view must
    never fail whole because one region is dark."""
    from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer
    from k8s_dra_driver_tpu.pkg import tracing

    now = time.time()
    merged: List[tuple] = []
    skipped: List[List[str]] = []
    latency_lines: List[str] = []
    reachable: List[tuple] = []   # (cluster, client, history, staleness)
    seen_decisions: set = set()
    trace_ids: set = set()
    for cname in sorted(clusters):
        capi = RemoteAPIServer(clusters[cname])
        try:
            hist = capi.history
        except OSError as exc:
            skipped.append(["-", cname, "-", "SKIPPED",
                            f"unreachable: {exc}", "-"])
            continue
        if hist is None:
            skipped.append(["-", cname, "-", "SKIPPED",
                            "no history store attached "
                            "(pre-flight-recorder peer)", "-"])
            continue
        rs = capi.replica_status()
        staleness = (f"wm={rs.get('watermark', 0)}"
                     f"/lag={rs.get('lag_records', 0)}"
                     if rs is not None else "fresh")
        reachable.append((cname, capi, hist, staleness))
        obj = capi.try_get(kind, name, namespace)
        if obj is not None:
            # A workload moved across the fleet carries its originating
            # trace in an annotation (tracing.inject_context) — the seed
            # for the cross-cluster stitch below.
            ctx = tracing.extract_context(obj.meta.annotations)
            if ctx is not None:
                trace_ids.add(ctx.trace_id)
        decisions = hist.decisions_for(kind, namespace, name)
        for r in decisions:
            seen_decisions.add((cname, r.wall, r.controller, r.name,
                                r.outcome))
            if r.trace_id:
                trace_ids.add(r.trace_id)
        for wall, pri, row in explain_timeline_entries(
                capi, obj, decisions, now):
            if row[3] != "-":
                trace_ids.add(row[3])
            merged.append((wall, pri,
                           [row[0], cname, staleness] + row[1:]))
        if latency and not latency_lines:
            latency_lines = lifecycle_breakdown_lines(
                capi, kind, namespace, name)
    # Second pass — trace stitching: pull in every cluster's decisions
    # that share the object's trace ids but were recorded against OTHER
    # objects (federation/spill on Cluster/..., scheduler/bind on the
    # consumer Pod), so the fleet-level causal chain appears on the
    # object's own timeline.
    if trace_ids:
        for cname, capi, hist, staleness in reachable:
            try:
                extra = hist.decisions_by_trace(sorted(trace_ids))
            except (OSError, AttributeError):
                continue
            fresh = [r for r in extra
                     if (cname, r.wall, r.controller, r.name, r.outcome)
                     not in seen_decisions]
            for wall, pri, row in explain_timeline_entries(
                    capi, None, fresh, now):
                merged.append((wall, pri,
                               [row[0], cname, staleness] + row[1:]))
    merged.sort(key=lambda t: (t[0], t[1]))
    queried = len(reachable)
    lines = [f"Name:       {name}"]
    if namespace:
        lines.append(f"Namespace:  {namespace}")
    lines += [f"Kind:       {kind}",
              f"Clusters:   {queried} queried, {len(skipped)} skipped"]
    rows = [row for _, _, row in merged] + skipped
    if rows:
        lines += ["Timeline:"] + _table(
            [["TIME", "CLUSTER", "STALENESS", "SOURCE", "WHAT", "TRACE"]]
            + rows)
    else:
        lines.append("Timeline:   <none>")
    if latency:
        lines += latency_lines or [
            "Latency:    <not profiled on any reachable cluster>"]
    return "\n".join(lines)


def describe_object(api, kind: str, name: str, namespace: str = "") -> str:
    """Render the `kubectl describe` view: identity, kind-specific status,
    conditions, and the deduplicated Event table."""
    obj = api.get(kind, name, namespace)
    now = time.time()
    lines = [f"Name:       {obj.meta.name}"]
    if obj.meta.namespace:
        lines.append(f"Namespace:  {obj.meta.namespace}")
    lines += [f"Kind:       {obj.kind}",
              f"UID:        {obj.meta.uid}"]
    if obj.meta.labels:
        lines.append("Labels:     " + ",".join(
            f"{k}={v}" for k, v in sorted(obj.meta.labels.items())))
    if obj.meta.annotations:
        lines.append("Annotations: " + ",".join(
            f"{k}={v}" for k, v in sorted(obj.meta.annotations.items())))
    lines += _describe_body(api, obj)
    lines += _events_lines(api, obj, now)
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import json
    import os
    import time as _time

    from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer
    from k8s_dra_driver_tpu.k8s.objects import NotFoundError
    from k8s_dra_driver_tpu.k8s.serialize import to_wire

    parser = argparse.ArgumentParser("tpu-kubectl",
                                     description="kubectl-style CLI for the TPU DRA stack")
    parser.add_argument("--server", default=os.environ.get("TPU_KUBECTL_SERVER", ""),
                        help="API server URL [TPU_KUBECTL_SERVER]")
    parser.add_argument("--cluster", default="",
                        help="route to a federated cluster: a name from "
                        "TPU_KUBECTL_CLUSTERS (\"name=url,name2=url2\") or a "
                        "URL. Follower answers are stamped (stderr) with "
                        "their replication watermark so staleness is visible")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_apply = sub.add_parser("apply")
    p_apply.add_argument("-f", "--filename", required=True)

    p_get = sub.add_parser("get")
    p_get.add_argument("kind")
    p_get.add_argument("name", nargs="?")
    p_get.add_argument("-n", "--namespace", default=None)
    p_get.add_argument("-A", "--all-namespaces", action="store_true")
    p_get.add_argument("-o", "--output", choices=("table", "json", "yaml"),
                       default="table")

    p_desc = sub.add_parser(
        "describe",
        help="status, conditions, and deduped events for one object")
    p_desc.add_argument("kind")
    p_desc.add_argument("name")
    p_desc.add_argument("-n", "--namespace", default=None)

    p_explain = sub.add_parser(
        "explain",
        help="merged causal timeline for one object: events + controller "
        "decision records + telemetry sparkline, each row with its trace id")
    p_explain.add_argument("kind")
    p_explain.add_argument("name")
    p_explain.add_argument("-n", "--namespace", default=None)
    p_explain.add_argument("--all-clusters", action="store_true",
                           help="fan out over TPU_KUBECTL_CLUSTERS and "
                           "merge every cluster's timeline into one "
                           "wall-ordered view with per-cluster provenance "
                           "and replication staleness")
    p_explain.add_argument("--latency", action="store_true",
                           help="append the claim's critical-path phase "
                           "breakdown (lifecycle analyzer)")

    p_top = sub.add_parser(
        "top",
        help="sorted utilization tables (nodes from a /metrics scrape, "
        "claims/computedomains from their status utilizationSummary)")
    p_top.add_argument("kind",
                       help="nodes | claims | computedomains | servinggroups")
    p_top.add_argument("-n", "--namespace", default=None)
    p_top.add_argument("-A", "--all-namespaces", action="store_true")
    p_top.add_argument("--metrics-url",
                       default=os.environ.get("TPU_KUBECTL_METRICS", ""),
                       help="base URL of a /metrics endpoint (required for "
                       "`top nodes`) [TPU_KUBECTL_METRICS]")
    p_top.add_argument("--history", action="store_true",
                       help="add MEAN-1M/P95-1M columns from the flight "
                       "recorder's downsampled one-minute tier")
    p_top.add_argument("--all-clusters", action="store_true",
                       help="fan out over TPU_KUBECTL_CLUSTERS: one table "
                       "with a CLUSTER column (nodes scrape each "
                       "cluster's /metrics)")

    p_fed = sub.add_parser(
        "federation",
        help="fleet-level views over TPU_KUBECTL_CLUSTERS")
    p_fed.add_argument("verb", choices=("status",),
                       help="status: per-peer replication watermark, lag, "
                       "reconnects, and last heartbeat")

    p_del = sub.add_parser("delete")
    p_del.add_argument("kind")
    p_del.add_argument("name")
    p_del.add_argument("-n", "--namespace", default="")

    p_wait = sub.add_parser("wait")
    p_wait.add_argument("kind")
    p_wait.add_argument("name")
    p_wait.add_argument("-n", "--namespace", default="")
    p_wait.add_argument("--for", dest="condition", default="Running",
                        help="Pod phase / CD status to wait for, or 'deleted'")
    p_wait.add_argument("--timeout", type=float, default=60.0)

    p_ann = sub.add_parser("annotate")
    p_ann.add_argument("kind")
    p_ann.add_argument("name")
    p_ann.add_argument("pairs", nargs="+", metavar="KEY=VALUE",
                       help="annotations to set (KEY- removes KEY)")
    p_ann.add_argument("-n", "--namespace", default="")

    args = parser.parse_args(argv)
    if args.cluster:
        args.server = _resolve_cluster(args.cluster)
    # Fan-out commands address the fleet through TPU_KUBECTL_CLUSTERS,
    # not one --server.
    fanout = getattr(args, "all_clusters", False) or args.cmd == "federation"
    if not args.server and not fanout:
        raise SystemExit("error: --server (or TPU_KUBECTL_SERVER) is required")
    api = RemoteAPIServer(args.server) if args.server else None
    if args.cluster:
        # Staleness stamp for read-replica answers: every row a follower
        # prints is only as fresh as its applied replication watermark.
        # Stderr keeps `-o json` parseable; leaders stamp nothing.
        rs = api.replica_status()
        if rs is not None:
            import sys as _sys

            print(f"# cluster {args.cluster}: read replica at replication "
                  f"watermark {rs.get('watermark', 0)} "
                  f"(lag {rs.get('lag_records', 0)} records)",
                  file=_sys.stderr)

    if args.cmd == "federation":
        clusters = _cluster_map()
        if not clusters:
            raise SystemExit(
                "error: federation status needs TPU_KUBECTL_CLUSTERS "
                "(\"name=url,name2=url2\")")
        from k8s_dra_driver_tpu.federation.query import (
            federation_status_rows,
        )

        statuses: Dict[str, Any] = {}
        skipped_rows = []
        for cname in sorted(clusters):
            capi = RemoteAPIServer(clusters[cname])
            try:
                statuses[cname] = capi.replica_status()
            except OSError as exc:
                skipped_rows.append(
                    [cname, "SKIPPED", f"unreachable: {exc}",
                     "-", "-", "-"])
        rows = [["PEER", "ROLE", "WATERMARK", "LAG", "RECONNECTS",
                 "LAST-HEARTBEAT"]]
        rows += federation_status_rows(statuses, now=_time.time())
        rows += skipped_rows
        _print_table(rows)
        return 0

    if args.cmd == "apply":
        if args.filename == "-":  # kubectl semantics: manifests on stdin
            import sys as _sys

            created = [api.create(o) for o in load_manifests(_sys.stdin.read())]
        else:
            created = apply_file(api, args.filename)
        for obj in created:
            print(f"{obj.kind.lower()}/{obj.meta.name} created")
        return 0

    kind = _resolve_kind(args.kind)
    if args.cmd == "top":
        if getattr(args, "all_clusters", False):
            clusters = _cluster_map()
            if not clusters:
                raise SystemExit(
                    "error: --all-clusters needs TPU_KUBECTL_CLUSTERS "
                    "(\"name=url,name2=url2\")")
            if kind not in ("Node", "ResourceClaim", "ComputeDomain",
                            "ServingGroup"):
                raise SystemExit(
                    "error: top supports nodes, claims, computedomains, "
                    "and servinggroups")
            if getattr(args, "all_namespaces", False):
                list_ns = args.namespace
            else:
                list_ns = args.namespace or "default"
            _print_table(top_rows_all_clusters(
                clusters, kind, namespace=list_ns, history=args.history))
            return 0
        if kind == "Node":
            if not args.metrics_url:
                raise SystemExit(
                    "error: top nodes reads per-chip gauges from a scrape; "
                    "pass --metrics-url (or TPU_KUBECTL_METRICS)")
            import urllib.request

            url = args.metrics_url.rstrip("/")
            if not url.endswith("/metrics"):
                url += "/metrics"
            with urllib.request.urlopen(url, timeout=10) as resp:
                _print_table(top_node_rows(resp.read().decode()))
            return 0
        if kind not in ("ResourceClaim", "ComputeDomain", "ServingGroup"):
            raise SystemExit(
                "error: top supports nodes, claims, computedomains, and "
                "servinggroups")
        if getattr(args, "all_namespaces", False):
            list_ns = args.namespace
        else:
            list_ns = args.namespace or "default"
        objs = api.list(kind, namespace=list_ns)
        hist = getattr(api, "history", None) if args.history else None
        if args.history and hist is None:
            raise SystemExit("error: --history needs a server with a flight "
                             "recorder attached (sim --persist or default)")
        if kind == "ResourceClaim":
            _print_table(top_claim_rows(objs, history=hist))
        elif kind == "ComputeDomain":
            _print_table(top_domain_rows(objs, history=hist))
        else:
            _print_table(top_servinggroup_rows(objs))
        return 0

    if args.cmd == "get":
        if args.name and getattr(args, "all_namespaces", False):
            # kubectl refuses this combination too: a name lookup is
            # namespace-scoped, so -A would silently mean "default".
            raise SystemExit(
                "error: a resource cannot be retrieved by name across all "
                "namespaces (drop -A or add -n <namespace>)"
            )
        if args.name:
            objs = [api.get(kind, args.name, _default_namespace(kind, args.namespace or ""))]
        else:
            # kubectl semantics: a bare list means the default namespace
            # (cluster-scoped kinds and -A list everything).
            if getattr(args, "all_namespaces", False) or kind in _CLUSTER_SCOPED:
                list_ns = args.namespace
            else:
                list_ns = args.namespace or "default"
            objs = api.list(kind, namespace=list_ns)
        if args.output == "json":
            docs = [to_wire(o) for o in objs]
            if api.last_staleness is not None:
                # Read-replica answer: wrap in an envelope carrying the
                # machine-readable staleness stamp (the X-Replication-*
                # header pair the list/get just returned). Fresh servers
                # keep the historical plain-array shape so existing
                # `... -o json | python -c "json.load..."` pipelines are
                # untouched.
                print(json.dumps(
                    {"items": docs, "staleness": api.last_staleness},
                    indent=1, sort_keys=True))
            else:
                print(json.dumps(docs, indent=1, sort_keys=True))
        elif args.output == "yaml":
            # A single named object renders as one document (scriptable
            # `get cd x -o yaml | yq .status.conditions`); lists as a
            # kubectl-style items wrapper.
            if args.name:
                print(yaml.safe_dump(to_wire(objs[0]), sort_keys=True),
                      end="")
            else:
                print(yaml.safe_dump({"items": [to_wire(o) for o in objs]},
                                     sort_keys=True), end="")
        else:
            rows = [["NAMESPACE", "NAME", "STATUS"]] + [_summary_row(o) for o in objs]
            widths = [max(len(r[i]) for r in rows) for i in range(3)]
            for r in rows:
                print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        return 0

    if args.cmd == "describe":
        print(describe_object(
            api, kind, args.name, _default_namespace(kind, args.namespace or "")))
        return 0

    if args.cmd == "explain":
        ns = _default_namespace(kind, args.namespace or "")
        if args.all_clusters:
            clusters = _cluster_map()
            if not clusters:
                raise SystemExit(
                    "error: --all-clusters needs TPU_KUBECTL_CLUSTERS "
                    "(\"name=url,name2=url2\")")
            print(explain_all_clusters(clusters, kind, args.name, ns,
                                       latency=args.latency))
        else:
            print(explain_object(api, kind, args.name, ns,
                                 latency=args.latency))
        return 0

    if args.cmd == "delete":
        api.delete(kind, args.name, _default_namespace(kind, args.namespace))
        print(f"{args.kind.lower()}/{args.name} deleted")
        return 0

    if args.cmd == "annotate":
        def mutate(obj, pairs=args.pairs):
            for pair in pairs:
                if pair.endswith("-") and "=" not in pair:
                    obj.meta.annotations.pop(pair[:-1], None)
                else:
                    k, _, v = pair.partition("=")
                    obj.meta.annotations[k] = v
        api.update_with_retry(kind, args.name, _default_namespace(kind, args.namespace), mutate)
        print(f"{args.kind.lower()}/{args.name} annotated")
        return 0

    if args.cmd == "wait":
        wait_ns = _default_namespace(kind, args.namespace)
        deadline = _time.monotonic() + args.timeout
        while _time.monotonic() < deadline:
            try:
                obj = api.get(kind, args.name, wait_ns)
            except NotFoundError:
                if args.condition == "deleted":
                    print(f"{args.kind.lower()}/{args.name} deleted")
                    return 0
                _time.sleep(0.2)
                continue
            state = _summary_row(obj)[2]
            # Exact-token match: "--for=Ready" must not match "NotReady";
            # pod states render as "Running (ready)" so accept a phase prefix.
            reached = state == args.condition or state.startswith(args.condition + " ")
            if args.condition != "deleted" and reached:
                print(f"{args.kind.lower()}/{args.name} is {state}")
                return 0
            _time.sleep(0.2)
        raise SystemExit(
            f"error: timed out waiting for {args.kind}/{args.name} "
            f"to reach {args.condition!r}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
