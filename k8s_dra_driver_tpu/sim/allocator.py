"""DRA structured-parameters allocator.

Implements what the Kubernetes scheduler's DRA plugin does with published
ResourceSlices: match claim requests to devices via DeviceClass + selectors,
honoring KEP-4815 counter consumption so overlapping devices (chips vs the
subslices containing them) are never double-allocated — the property the
reference encodes for MIG memory slices
(/root/reference/cmd/gpu-kubelet-plugin/partitions.go:53-246).
"""

from __future__ import annotations

import logging
import re
import time
from collections import defaultdict
from types import SimpleNamespace
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.pkg import placement
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Histogram, Registry
from k8s_dra_driver_tpu.pkg.workqueue import WORKQUEUE_SECONDS_BUCKETS
from k8s_dra_driver_tpu.k8s.core import (
    AllocationResult,
    DEVICE_CLASS,
    Device,
    DeviceRequestAllocationResult,
    RESOURCE_CLAIM,
    RESOURCE_SLICE,
    ResourceClaim,
    ResourceSlice,
)

log = logging.getLogger(__name__)


class AllocationError(Exception):
    pass


# A CEL comparison operator leaking into a string selector: '==', '!=',
# '<=', '>=' all put one of []!<>=] immediately before the first '=' (or
# an '=' right after it). The KEY side decides — values may contain
# anything, including more '='.
_CEL_OPERATOR_KEY = re.compile(r"[!<>=]$")


class _MatchPlan:
    """Per-request device matcher, compiled ONCE per request instead of
    re-parsed per device: legacy ``attr=value`` selectors are regex-parsed
    at plan build (a malformed one fails the request up front, same
    observable error as before), and CEL selectors are compiled to closures
    (celmini caches compilation; the plan pins the compiled fns so the hot
    loop does zero dict/regex work per device)."""

    __slots__ = ("driver", "match_attrs", "legacy_pairs", "cel_fns",
                 "_cel_error")

    def __init__(self, driver: str, match_attrs: Dict[str, object],
                 legacy_selectors: Sequence[str],
                 cel_selectors: Sequence[str]):
        self.driver = driver
        self.match_attrs = dict(match_attrs)
        self.legacy_pairs: List[Tuple[str, str]] = []
        for sel in legacy_selectors:
            # Legacy sim-only attr=value strings, split on the FIRST '='
            # (the pre-PR-1 partition("=") behavior): the key is bare, the
            # value may itself contain '=' ("key=a=b" -> value "a=b",
            # e.g. base64ish or flag-shaped attribute values). A CEL
            # expression that arrives here as a plain string must still
            # fail loudly — its '==' / '!=' / '>=' / '<=' leaves an
            # operator character on the key side or an '=' leading the
            # value — not silently look up a garbage attribute key and
            # match zero devices.
            key, sep, value = sel.partition("=")
            if (not sep or not key.strip()
                    or _CEL_OPERATOR_KEY.search(key.strip())
                    or value.startswith("=")):
                raise AllocationError(
                    f"malformed legacy selector {sel!r} (want attr=value; CEL "
                    f"selectors use the manifest form {{cel: {{expression}}}})")
            self.legacy_pairs.append((key.strip(), value.strip()))
        self.cel_fns = []
        self._cel_error: type = Exception
        if cel_selectors:
            # Real DRA selectors (class- or request-level), tagged as CEL
            # at manifest parse time by their k8s shape {cel: {expression}}
            # — never sniffed out of a string, so a legacy value containing
            # "device." can't be misrouted here.
            from k8s_dra_driver_tpu.k8s import celmini

            self._cel_error = celmini.CelError  # bound once, off the hot loop
            try:
                self.cel_fns = [celmini.compile_expression(e)
                                for e in cel_selectors]
            except celmini.CelError as e:
                raise AllocationError(f"bad CEL selector: {e}") from e

    def matches(self, dev: Device) -> bool:
        for k, v in self.match_attrs.items():
            if dev.attributes.get(k) != v:
                return False
        if self.cel_fns:
            # CEL sees `device.driver`; the Device object itself doesn't
            # carry it (the slice does), so bind it for evaluation.
            view = SimpleNamespace(driver=self.driver,
                                   attributes=dev.attributes,
                                   capacity=dev.capacity)
            try:
                if not all(bool(fn(view)) for fn in self.cel_fns):
                    return False
            except self._cel_error as e:
                raise AllocationError(f"bad CEL selector: {e}") from e
        for k, v in self.legacy_pairs:
            if str(dev.attributes.get(k)) != v:
                return False
        return True


def _device_matches(dev: Device, match_attributes: Dict[str, object],
                    selectors: List[str], cel_selectors: List[str] = (),
                    driver: str = "") -> bool:
    """One-shot matcher (tests, ad-hoc callers): builds a throwaway plan.
    The allocator's hot loop uses a per-request plan instead."""
    return _MatchPlan(driver, match_attributes, selectors,
                      list(cel_selectors)).matches(dev)


class AllocatorPassMetrics:
    """Per-pass decision telemetry: how much the scheduler probed and how
    much of that work the pass-scoped caches absorbed. Gauges carry the
    last completed pass (the partition-tuning signal MISO/Flex-MIG-style
    placement work needs per decision, not cumulatively)."""

    def __init__(self, registry: Registry):
        self.passes_total = registry.register(Counter(
            "tpu_dra_allocator_passes_total", "Completed allocator passes."))
        self.pass_seconds = registry.register(Histogram(
            "tpu_dra_allocator_pass_seconds",
            "Wall time of one allocator pass (begin_pass to end_pass).",
            buckets=WORKQUEUE_SECONDS_BUCKETS,
        ))
        self.nodes_probed = registry.register(Gauge(
            "tpu_dra_allocator_pass_nodes_probed",
            "allocate_on_node probes in the last pass."))
        self.plans_compiled = registry.register(Gauge(
            "tpu_dra_allocator_pass_plans_compiled",
            "Match plans compiled (selector parse + CEL compile) last pass."))
        self.plans_cached = registry.register(Gauge(
            "tpu_dra_allocator_pass_plans_cached",
            "Match-plan requests served from the pass cache last pass."))
        self.commits = registry.register(Gauge(
            "tpu_dra_allocator_pass_commits",
            "Allocations committed in the last pass."))
        self.rollbacks = registry.register(Gauge(
            "tpu_dra_allocator_pass_rollbacks",
            "Allocations rolled back in the last pass."))
        self.feasibility_checked = registry.register(Gauge(
            "tpu_dra_allocator_pass_feasibility_checked",
            "Nodes examined by the feasibility pre-filter last pass."))
        self.feasible_nodes = registry.register(Gauge(
            "tpu_dra_allocator_pass_feasible_nodes",
            "Nodes the feasibility pre-filter admitted last pass "
            "(only these are probed with allocate_on_node)."))
        self.infeasible_skipped = registry.register(Gauge(
            "tpu_dra_allocator_pass_infeasible_skipped",
            "Nodes the feasibility pre-filter excluded last pass — "
            "probes the indexed scheduler never issued."))
        self.feasibility_cache_hits = registry.register(Gauge(
            "tpu_dra_allocator_pass_feasibility_cache_hits",
            "Pods whose candidate list was served from the pass-shared "
            "admission snapshot last pass instead of a fresh "
            "feasibility computation."))
        self.frag_largest_free = registry.register(Gauge(
            "tpu_dra_node_frag_largest_free_profile",
            "Chips in the largest still-placeable subslice profile "
            "(whole-host included) per node — the fragmentation signal: "
            "free chips without a large placeable profile are stranded.",
            ("node",),
        ))
        self.placement_score = registry.register(Histogram(
            "tpu_dra_alloc_placement_score",
            "Fragmentation score of each placement the best-fit allocator "
            "chose: surviving larger-profile placements the choice "
            "destroyed (0 = perfectly packing choice).",
            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 16.0, 32.0),
        ))

    def publish(self, stats: Dict[str, int], seconds: float) -> None:
        self.passes_total.inc()
        self.pass_seconds.observe(value=seconds)
        self.nodes_probed.set(value=float(stats["nodes_probed"]))
        self.plans_compiled.set(value=float(stats["plans_compiled"]))
        self.plans_cached.set(value=float(stats["plans_cached"]))
        self.commits.set(value=float(stats["commits"]))
        self.rollbacks.set(value=float(stats["rollbacks"]))
        self.feasibility_checked.set(value=float(stats["feasibility_checked"]))
        self.feasible_nodes.set(value=float(stats["feasible_nodes"]))
        self.infeasible_skipped.set(value=float(stats["infeasible_skipped"]))
        self.feasibility_cache_hits.set(
            value=float(stats["feasibility_cache_hits"]))


def _pass_stats() -> Dict[str, int]:
    return {"nodes_probed": 0, "plans_compiled": 0, "plans_cached": 0,
            "commits": 0, "rollbacks": 0, "feasibility_checked": 0,
            "feasible_nodes": 0, "infeasible_skipped": 0,
            "feasibility_cache_hits": 0}


class Allocator:
    def __init__(self, api: APIServer, metrics_registry: Optional[Registry] = None,
                 best_fit: bool = True):
        self.api = api
        # Fragmentation-scored best-fit placement + packing-aware node
        # rank. False reverts to the PR 3 behavior (slice-order first-fit,
        # most-free-first) — kept as the bench_placement baseline and an
        # escape hatch, not a supported production mode.
        self.best_fit = best_fit
        self.metrics = AllocatorPassMetrics(metrics_registry or Registry())
        # Stats of the last completed pass (mirrors the gauges; handy for
        # the sim's scheduler-pass span attributes and tests).
        self.last_pass_stats: Dict[str, int] = _pass_stats()
        self._pass_snapshot = None  # (slices, allocations) for one pass
        # fingerprint -> (slices, index): slices survive across passes
        # until any ResourceSlice changes (see begin_pass).
        self._slice_cache: Optional[tuple] = None
        # Static per-(driver, node) capacity summaries + per-plan match
        # cache backing feasible_nodes(); invalidated when the slice or
        # DeviceClass fingerprint moves (see _feasibility_state).
        self._feas_cache: Optional[dict] = None
        # Nodes with a published frag gauge series — forgotten when the
        # node's slice disappears so /metrics never reports fragmentation
        # for deleted nodes.
        self._frag_nodes: set = set()
        # (claim_fp, slice_fp) -> (allocations, consumed) surviving across
        # passes while no ResourceClaim changed: a quiet cluster's
        # begin_pass is O(1) instead of O(claims). Any commit during a
        # pass writes the claim through the API first, so the fingerprint
        # always moves before the cached list could go stale.
        self._alloc_cache: Optional[tuple] = None

    # -- pass-scoped snapshot -------------------------------------------------

    def _snapshot_slices(self):
        """List ResourceSlices for a pass, reusing the previous pass's
        deepcopied list (and its device index) when the store's kind
        fingerprint says nothing changed. Slices are read-only to the
        allocator, and listing them from the in-memory store deepcopies
        256-chip counter sets per node — the dominant cost (and, via GC
        over the copy graph, the dominant tail) of the 64-node storm."""
        fp_fn = getattr(self.api, "kind_fingerprint", None)
        if fp_fn is None:
            return list(self.api.list(RESOURCE_SLICE)), {}
        fp = fp_fn(RESOURCE_SLICE)
        if self._slice_cache is not None and self._slice_cache[0] == fp:
            return self._slice_cache[1], self._slice_cache[2]
        slices = list(self.api.list(RESOURCE_SLICE))
        index = {
            (s.driver, s.node_name): {d.name: d for d in s.devices}
            for s in slices
        }
        self._slice_cache = (fp, slices, index)
        return slices, index

    def begin_pass(self) -> None:
        """Snapshot slices + existing claim allocations for one scheduler
        pass. Without it every allocate_on_node call re-lists every claim
        and slice — O(pods × nodes × claims) per pass, which dominates at
        cluster scale (64 nodes / 128 pods: ~115 s → ~1 s). Allocations
        written during the pass must be recorded with ``commit()`` so the
        snapshot can never double-book by construction.

        The pass also carries incremental per-node consumed-counter
        accounting: built here in ONE scan of the allocation list, then
        updated by ``commit()``/``rollback()`` — so a whole scheduler pass
        is O(allocations) total instead of re-scanning every allocation for
        every pod × node probe (O(pods × allocations))."""
        slices, index = self._snapshot_slices()
        index = dict(index)
        if not index:
            # No fingerprint-backed slice cache (api without
            # kind_fingerprint): build the device index here — the
            # consumed cache below is only correct against a real index.
            index = {
                (s.driver, s.node_name): {d.name: d for d in s.devices}
                for s in slices
            }
        fp_fn = getattr(self.api, "kind_fingerprint", None)
        alloc_fps = (None if fp_fn is None else
                     (fp_fn(RESOURCE_CLAIM), fp_fn(RESOURCE_SLICE)))
        if (alloc_fps is not None and self._alloc_cache is not None
                and self._alloc_cache[0] == alloc_fps):
            allocations, consumed = self._alloc_cache[1], self._alloc_cache[2]
            used_masks = self._alloc_cache[3]
        else:
            allocations = [
                c.allocation for c in self.api.list(RESOURCE_CLAIM)
                if c.allocation is not None
            ]
            consumed = {}
            used_masks = {}
            for alloc in allocations:
                self._accrue(consumed, index, alloc, +1)
                self._accrue_mask(used_masks, index, alloc, +1)
            if alloc_fps is not None:
                self._alloc_cache = (alloc_fps, allocations, consumed,
                                     used_masks)
        # Per-node {driver -> slice} — built once so allocate_on_node
        # reuses the pass's device view instead of re-listing/rebuilding
        # slices_by_driver on every node probe.
        by_node: Dict[str, Dict[str, ResourceSlice]] = {}
        for s in slices:
            by_node.setdefault(s.node_name, {})[s.driver] = s
        self._pass_snapshot = {
            "slices": slices,
            "allocations": allocations,
            "index": index,  # (driver, node) -> {name -> Device}
            "slices_by_node": by_node,  # node -> {driver -> slice}
            "consumed": consumed,  # node -> counter_set -> counter -> used
            # node -> int chip-bitmask of allocated chips, maintained
            # incrementally next to `consumed` (commit/rollback) — the
            # placement engine's O(1) free-mask source.
            "used_masks": used_masks,
            "classes": {},  # DeviceClass name -> (driver, attrs, cel)
            "plans": {},  # content key -> (driver, _MatchPlan)
            "stats": _pass_stats(),
            # id(result) -> (result, scores): placement scores of probes
            # made this pass, observed once at commit(). Held OFF the
            # AllocationResult itself — the result is installed verbatim
            # into the stored claim and frozen at publish, so it must not
            # carry mutable allocator bookkeeping. The strong ref pins the
            # id against reuse; unclaimed entries die with the pass.
            "pending_scores": {},
            "t0": time.perf_counter(),
        }

    @staticmethod
    def _accrue(consumed: Dict, index: Dict, alloc, sign: int) -> None:
        """Add (or with sign=-1 remove) one allocation's counter consumption
        to the per-node incremental cache."""
        if alloc is None or not alloc.node_name:
            return
        node = consumed.setdefault(
            alloc.node_name, defaultdict(lambda: defaultdict(int)))
        for r in alloc.devices:
            dev = index.get((r.driver, alloc.node_name), {}).get(r.device)
            if dev is None:
                continue
            for cc in dev.consumes_counters:
                for cname, ctr in cc.counters.items():
                    node[cc.counter_set][cname] += sign * ctr.value

    @staticmethod
    def _accrue_mask(masks: Dict[str, int], index: Dict, alloc,
                     sign: int) -> None:
        """Fold one allocation's chip coverage into the per-node used-chip
        bitmask. Chip counters cap at 1, so set/clear is exact: no two
        live allocations can hold the same chip bit."""
        if alloc is None or not alloc.node_name:
            return
        bits = 0
        for r in alloc.devices:
            dev = index.get((r.driver, alloc.node_name), {}).get(r.device)
            if dev is not None:
                bits |= placement.chip_bits_of_device(dev)
        if not bits:
            return
        if sign > 0:
            masks[alloc.node_name] = masks.get(alloc.node_name, 0) | bits
        else:
            masks[alloc.node_name] = masks.get(alloc.node_name, 0) & ~bits

    def commit(self, alloc) -> None:
        """Record an allocation written to the API during the active pass —
        it joins the snapshot's allocation list AND the incremental
        consumed-counter cache, so every later allocate_on_node counts it
        without a rescan. No-op outside a pass (live listing sees the write
        directly)."""
        if self._pass_snapshot is not None and alloc is not None:
            self._pass_snapshot["allocations"].append(alloc)
            self._pass_snapshot["stats"]["commits"] += 1
            entry = self._pass_snapshot["pending_scores"].pop(id(alloc), None)
            if entry is not None and entry[0] is alloc:  # observe exactly once
                for score in entry[1]:
                    self.metrics.placement_score.observe(value=score)
            self._accrue(self._pass_snapshot["consumed"],
                         self._pass_snapshot["index"], alloc, +1)
            self._accrue_mask(self._pass_snapshot["used_masks"],
                              self._pass_snapshot["index"], alloc, +1)

    def rollback(self, alloc) -> None:
        """Withdraw an allocation previously ``commit()``-ed this pass (the
        scheduler undid the placement, e.g. a sibling claim of the same pod
        failed on that node). Counter accounting is decremented exactly as
        commit incremented it, so re-allocation sees the same state as a
        from-scratch rescan."""
        if self._pass_snapshot is None or alloc is None:
            return
        allocations = self._pass_snapshot["allocations"]
        for i, a in enumerate(allocations):
            # Identity first (the common case: the object commit() took),
            # falling back to value equality so a caller holding an equal
            # reconstruction of the allocation still withdraws it.
            if a is alloc or a == alloc:
                del allocations[i]
                self._pass_snapshot["stats"]["rollbacks"] += 1
                self._accrue(self._pass_snapshot["consumed"],
                             self._pass_snapshot["index"], alloc, -1)
                self._accrue_mask(self._pass_snapshot["used_masks"],
                                  self._pass_snapshot["index"], alloc, -1)
                return

    def end_pass(self) -> None:
        if self._pass_snapshot is not None:
            # While the snapshot is still active so the feasibility state
            # resolves against the pass's slice view, not a fresh listing.
            self._publish_frag_gauges(self._pass_snapshot)
        snap, self._pass_snapshot = self._pass_snapshot, None
        if snap is not None:
            self.last_pass_stats = snap["stats"]
            if snap["stats"]["commits"] or snap["stats"]["rollbacks"]:
                # The pass mutated the cached allocation list/consumed
                # counters in place; rebuild from the API next pass (test
                # harnesses may commit without an API write, so don't rely
                # on the fingerprint alone).
                self._alloc_cache = None
            self.metrics.publish(snap["stats"],
                                 time.perf_counter() - snap["t0"])

    def _publish_frag_gauges(self, snap: dict) -> None:
        """Per-node fragmentation gauge at pass end: chips in the largest
        profile still placeable on each placement-table-backed node. One
        AND+popcount sweep over the precomputed tables per node."""
        try:
            cache = self._feasibility_state()
        except Exception:  # noqa: BLE001 — telemetry must not fail a pass
            return
        used_masks = snap["used_masks"]
        seen = set()
        for (_, node), entry in cache["entries"].items():
            tables = entry.get("tables")
            if tables is None:
                continue
            largest = tables.largest_free_chips(
                used_masks.get(node, 0), entry["available"])
            self.metrics.frag_largest_free.set(node, value=float(largest))
            seen.add(node)
        for node in self._frag_nodes - seen:
            self.metrics.frag_largest_free.forget(node)
        self._frag_nodes = seen

    def _list_slices(self):
        if self._pass_snapshot is not None:
            return self._pass_snapshot["slices"]
        return self.api.list(RESOURCE_SLICE)

    def _list_allocations(self):
        if self._pass_snapshot is not None:
            return self._pass_snapshot["allocations"]
        return [c.allocation for c in self.api.list(RESOURCE_CLAIM)
                if c.allocation is not None]

    def _device_index(self, slices) -> Dict:
        """(driver, node) -> {device name -> Device}; cached in the pass
        snapshot so the storm doesn't re-index every slice per call."""
        if self._pass_snapshot is not None and self._pass_snapshot["index"]:
            return self._pass_snapshot["index"]
        index = {
            (s.driver, s.node_name): {d.name: d for d in s.devices}
            for s in slices
        }
        if self._pass_snapshot is not None:
            self._pass_snapshot["index"] = index
        return index

    # -- counter accounting --------------------------------------------------

    def _consumed_counters(self, node_name: str,
                           in_flight: Sequence = ()) -> Dict[str, Dict[str, int]]:
        """counter_set -> counter -> consumed, over all allocated claims on
        this node plus any ``in_flight`` AllocationResults computed but not
        yet committed (sibling claims of one pod scheduled together).

        This is the from-scratch rescan — O(allocations) per call. Inside a
        pass, ``_consumed_for_node`` serves the same answer from the
        incremental cache; this implementation is kept as the correctness
        oracle the property tests diff the cache against."""
        by_name = self._device_index(self._list_slices())
        consumed: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))

        def count(alloc) -> None:
            if alloc is None or alloc.node_name != node_name:
                return
            for r in alloc.devices:
                dev = by_name.get((r.driver, node_name), {}).get(r.device)
                if dev is None:
                    continue
                for cc in dev.consumes_counters:
                    for cname, ctr in cc.counters.items():
                        consumed[cc.counter_set][cname] += ctr.value

        for alloc in self._list_allocations():
            count(alloc)
        for alloc in in_flight:
            count(alloc)
        return consumed

    def _consumed_for_node(self, node_name: str,
                           in_flight: Sequence = ()) -> Dict[str, Dict[str, int]]:
        """Consumed counters for one node: the incremental cache inside a
        pass (O(in_flight) per call), the full rescan outside one."""
        snap = self._pass_snapshot
        if snap is None:
            return self._consumed_counters(node_name, in_flight)
        base = snap["consumed"].get(node_name)
        if not in_flight:
            if base is None:
                base = snap["consumed"].setdefault(
                    node_name, defaultdict(lambda: defaultdict(int)))
            return base
        # Overlay in-flight siblings on a copy so probing one node for one
        # pod never dirties the pass-wide cache.
        consumed: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
        if base is not None:
            for cs, counters in base.items():
                consumed[cs].update(counters)
        overlay = {node_name: consumed}
        for alloc in in_flight:
            if alloc is not None and alloc.node_name == node_name:
                self._accrue(overlay, snap["index"], alloc, +1)
        return consumed

    def _fits(self, rs: ResourceSlice, dev: Device,
              consumed: Dict[str, Dict[str, int]],
              pending: Dict[str, Dict[str, int]]) -> bool:
        available = {cs.name: cs.counters for cs in rs.shared_counters}
        for cc in dev.consumes_counters:
            caps = available.get(cc.counter_set)
            if caps is None:
                # Device consumes a counter set the slice doesn't share:
                # treat as unconstrained (e.g. channel/daemon devices).
                continue
            for cname, ctr in cc.counters.items():
                cap = caps.get(cname)
                if cap is None:
                    return False
                used = consumed[cc.counter_set][cname] + pending[cc.counter_set][cname]
                if used + ctr.value > cap.value:
                    return False
        return True

    # -- node-capacity feasibility index --------------------------------------

    def _feasibility_state(self) -> dict:
        """Static half of the node-capacity index: per (driver, node) the
        untainted devices, the slice's counter capacities, and total
        capacity units (the packing-rank ordering key), plus the set of
        attribute values present per attribute. Built once and reused until
        the ResourceSlice or DeviceClass kind fingerprint moves — the
        dynamic half (consumed counters) already lives in the pass snapshot
        and is maintained incrementally by commit()/rollback()."""
        fp_fn = getattr(self.api, "kind_fingerprint", None)
        if fp_fn is None:
            fps = None
        else:
            # The slice component must be the fingerprint of the slices the
            # index is actually built from: inside a pass that is the
            # snapshot (its fp was recorded at begin_pass), NOT the live
            # store — a slice deleted mid-pass must invalidate on the NEXT
            # pass, when the snapshot refreshes, not be masked forever by a
            # cache stamped with the post-deletion fingerprint.
            snap = self._pass_snapshot
            if (snap is not None and self._slice_cache is not None
                    and snap["slices"] is self._slice_cache[1]):
                slice_fp = self._slice_cache[0]
            else:
                slice_fp = fp_fn(RESOURCE_SLICE)
            fps = (slice_fp, fp_fn(DEVICE_CLASS))
        cache = self._feas_cache
        if cache is not None and fps is not None and cache["fps"] == fps:
            return cache
        entries: Dict[Tuple[str, str], dict] = {}
        topologies: Dict[str, dict] = {}
        for s in self._list_slices():
            caps = {cs.name: {c: ctr.value for c, ctr in cs.counters.items()}
                    for cs in s.shared_counters}
            untainted = [
                d for d in s.devices
                if not any(t.effect in ("NoSchedule", "NoExecute")
                           for t in d.taints)
            ]
            attr_values: Dict[str, set] = {}
            for d in untainted:
                for k, v in d.attributes.items():
                    attr_values.setdefault(k, set()).add(v)
            entry = {
                "devices": untainted,
                "caps": caps,
                "cap_units": sum(v for cc in caps.values()
                                 for v in cc.values()),
                "attr_values": attr_values,
            }
            self._build_placement_state(s, untainted, entry)
            entries[(s.driver, s.node_name)] = entry
            if entry.get("topo") is not None:
                topologies[s.node_name] = entry["topo"]
        cap_units: Dict[str, int] = {}
        for (_, node), e in entries.items():
            cap_units[node] = cap_units.get(node, 0) + e["cap_units"]
        cache = {"fps": fps, "entries": entries, "match": {},
                 "nodes": frozenset(cap_units), "node_cap_units": cap_units,
                 "topologies": topologies}
        self._feas_cache = cache
        return cache

    @staticmethod
    def _build_placement_state(s: ResourceSlice, untainted, entry: dict) -> None:
        """Attach the bitmask placement view to one static index entry:
        the host's precomputed PlacementTables, a placement-availability
        bitmap (a placement is available iff an untainted device with that
        exact chip mask is published — a taint drops exactly its device's
        placements, endpoint chips stay placeable), per-device chip masks,
        and the node's grid/ICI-domain coordinates for host-set planning.
        Slices without TPU topology attributes get no placement state and
        keep the plain counter-probing path."""
        entry["tables"] = None
        entry["available"] = 0
        entry["dev_mask"] = {}
        entry["topo"] = None
        host_topo = slice_topo = ici = coord_s = None
        worker = None
        for d in s.devices:
            for k, v in d.attributes.items():
                if k.endswith("/hostTopology"):
                    host_topo = v
                elif k.endswith("/sliceTopology"):
                    slice_topo = v
                elif k.endswith("/iciDomain"):
                    ici = v
                elif k.endswith("/workerId"):
                    worker = v
                elif k.endswith("/hostCoord"):
                    coord_s = v
            if host_topo:
                break
        if not host_topo:
            return
        try:
            tables = placement.tables_for(host_topo)
        except ValueError:
            return
        entry["tables"] = tables
        available = 0
        chips_avail = 0
        dev_mask: Dict[str, int] = {}
        for d in untainted:
            bits = placement.chip_bits_of_device(d)
            if not bits:
                continue
            dev_mask[d.name] = bits
            idx = tables.by_mask.get(bits)
            if idx is not None:
                available |= 1 << idx
            if bits & (bits - 1) == 0:
                chips_avail |= bits
        # Whole-host placeability: every chip individually available AND no
        # published spanning device is tainted (an ICI-link taint lands on
        # spanning devices only — it must kill whole-host placements while
        # the endpoint chips stay schedulable).
        untainted_ids = {id(d) for d in untainted}
        spanning_tainted = any(
            placement.popcount(placement.chip_bits_of_device(d)) >= 2
            for d in s.devices if id(d) not in untainted_ids
        )
        if chips_avail == tables.full_mask and not spanning_tainted:
            available |= 1 << tables.whole_host_index
        entry["available"] = available
        entry["dev_mask"] = dev_mask
        topo = {"host_topology": host_topo, "slice_topology": slice_topo,
                "ici_domain": ici or "", "worker_id": worker,
                "host_coord": None}
        if coord_s:
            try:
                topo["host_coord"] = tuple(
                    int(v) for v in str(coord_s).split("x"))
            except ValueError:
                pass
        elif slice_topo is not None and worker is not None:
            # Older slices without the hostCoord attribute: derive it from
            # workerId with the same row-major tiling rule the tpulibs use.
            try:
                topo["host_coord"] = placement.host_grid_coord(
                    slice_topo, host_topo, int(worker))
            except (ValueError, TypeError):
                pass
        entry["topo"] = topo

    @staticmethod
    def _dev_fits_base(dev: Device, caps: Dict[str, Dict[str, int]],
                       consumed) -> bool:
        """Would this device fit with the node's CURRENT consumption alone
        (no pending/in-flight overlay)? Mirrors _fits(); any device a real
        allocation chooses necessarily passes this weaker check."""
        for cc in dev.consumes_counters:
            cap_set = caps.get(cc.counter_set)
            if cap_set is None:
                continue  # unconstrained counter set (channel/daemon)
            used_set = consumed.get(cc.counter_set) if consumed else None
            for cname, ctr in cc.counters.items():
                cap = cap_set.get(cname)
                if cap is None:
                    return False
                used = used_set.get(cname, 0) if used_set else 0
                if used + ctr.value > cap:
                    return False
        return True

    def _matching_devices(self, cache: dict, driver: str, node: str,
                          plan_key, plan: _MatchPlan) -> list:
        """Untainted devices on (driver, node) matching one request's plan.
        Match results depend only on slice + class content, so they are
        cached alongside the static index and survive across passes."""
        entry = cache["entries"].get((driver, node))
        if entry is None:
            return []
        mkey = (driver, node, plan_key)
        hit = cache["match"].get(mkey)
        if hit is None:
            present = entry["attr_values"]
            if any(v not in present.get(k, ())
                   for k, v in plan.match_attrs.items()):
                hit = []  # a required attribute value exists on no device
            else:
                hit = [d for d in entry["devices"] if plan.matches(d)]
            cache["match"][mkey] = hit
        return hit

    def node_topologies(self) -> Dict[str, dict]:
        """node -> {ici_domain, slice_topology, host_topology, host_coord,
        worker_id} from the static index — the input the host-grid domain
        planner (pkg.placement.choose_host_block) consumes."""
        return dict(self._feasibility_state()["topologies"])

    def placement_state(self, driver: str, node: str) -> Optional[dict]:
        """Bitmask placement view of one node (tests, telemetry): the
        host's PlacementTables, the availability bitmap (taints applied),
        per-device chip masks, and the current used-chip mask."""
        entry = self._feasibility_state()["entries"].get((driver, node))
        if entry is None or entry.get("tables") is None:
            return None
        return {
            "tables": entry["tables"],
            "available": entry["available"],
            "dev_mask": dict(entry["dev_mask"]),
            "used_mask": self._used_mask(node),
        }

    def placement_overview(self, driver: str) -> Dict[str, dict]:
        """Bitmask placement view of EVERY placement-table-backed node for
        one driver in a single allocation scan: node -> {tables, available,
        dev_mask, used_mask}. This is the rebalancer's read surface — the
        same state behind the ``tpu_dra_node_frag_largest_free_profile``
        gauge, but as masks it can plan repack moves against."""
        cache = self._feasibility_state()
        index = self._device_index(self._list_slices())
        masks: Dict[str, int] = {}
        for alloc in self._list_allocations():
            self._accrue_mask(masks, index, alloc, +1)
        out: Dict[str, dict] = {}
        for (drv, node), entry in cache["entries"].items():
            if drv != driver or entry.get("tables") is None:
                continue
            out[node] = {
                "tables": entry["tables"],
                "available": entry["available"],
                "dev_mask": dict(entry["dev_mask"]),
                "used_mask": masks.get(node, 0),
                # device name -> published `type` attribute (tpu/subslice/
                # vfio/...), so the rebalancer can pin passthrough devices.
                "dev_type": {
                    d.name: d.attributes.get("type", "")
                    for d in entry["devices"]
                },
            }
        return out

    def feasible_nodes(self, claims, nodes: Optional[Iterable[str]] = None,
                       reasons: Optional[Dict[str, str]] = None) -> List[str]:
        """Pre-filter for the scheduler: node names on which every request
        of every claim could POSSIBLY be satisfied, in packing-aware order
        — tightest-fit first for partial-node claim sets, emptiest-first
        when any request is mode=All (whole-host/domain) or with
        best_fit=False; ties by name, so a fresh cluster keeps the
        deterministic name order. Checks necessary conditions only — a slice for the
        request's driver, enough plan-matching untainted devices, and
        enough of them individually fitting the node's current consumed
        counters — so it never excludes a node allocate_on_node (the
        probe-every-node oracle) would have placed on; it may admit nodes
        a full probe then rejects (joint sibling fit, within-claim counter
        accumulation). ``claims``: one ResourceClaim or a sequence (a
        pod's unallocated claims, intersected). ``reasons``: optional dict
        the filter fills with node -> first human-readable rejection reason
        — the per-node verdict the scheduler's FailedScheduling /
        AllocationFailed events narrate."""
        if isinstance(claims, ResourceClaim):
            claims = [claims]
        cache = self._feasibility_state()
        snap = self._pass_snapshot
        plans = []
        for claim in claims:
            for req in claim.requests:
                driver, plan = self._match_plan(req)
                plan_key = (req.device_class_name, tuple(req.selectors),
                            tuple(getattr(req, "cel_selectors", ())))
                plans.append((req, driver, plan_key, plan))
        candidates = cache["nodes"]
        if nodes is not None:
            candidates = candidates & set(nodes)
        cap_units = cache["node_cap_units"]
        # Packing-aware rank: partial-node claims probe the TIGHTEST
        # feasible node first (fewest free capacity units — small claims
        # pile onto already-fragmented hosts, preserving empty hosts for
        # whole-host/domain claims); whole-node claims (any mode=All
        # request) keep the emptiest-first order they need. best_fit=False
        # reverts to unconditional most-free-first (the PR 3 rank).
        emptiest_first = (not self.best_fit) or any(
            req.allocation_mode == "All" for req, _, _, _ in plans)
        scored = []
        for node in candidates:
            consumed = self._consumed_for_node(node)
            used = sum(v for counters in consumed.values()
                       for v in counters.values()) if consumed else 0
            if all(self._node_feasible(cache, node, req, driver, pk, plan,
                                       consumed if used else None)
                   for req, driver, pk, plan in plans):
                free = cap_units.get(node, 0) - used
                scored.append((-free if emptiest_first else free, node))
            elif reasons is not None:
                reasons[node] = self._infeasibility_reason(
                    cache, node, plans, consumed if used else None)
        if snap is not None:
            snap["stats"]["feasibility_checked"] += len(candidates)
            snap["stats"]["feasible_nodes"] += len(scored)
            snap["stats"]["infeasible_skipped"] += (
                len(candidates) - len(scored))
        scored.sort()
        return [node for _, node in scored]

    def note_feasible_cached(self, count: int) -> None:
        """The scheduler served one pod's candidate list from its
        pass-shared admission snapshot (no fresh computation). Count the
        served nodes exactly as a fresh feasible_nodes() call would, so
        ``probes <= feasible admitted`` stays a meaningful per-pass
        invariant under snapshot gang admission."""
        snap = self._pass_snapshot
        if snap is not None:
            snap["stats"]["feasible_nodes"] += count
            snap["stats"]["feasibility_cache_hits"] += 1

    def _infeasibility_reason(self, cache: dict, node: str, plans,
                              consumed) -> str:
        """Why feasible_nodes excluded one node: the first failing necessary
        condition, in request order, phrased for an Event message."""
        for req, driver, plan_key, plan in plans:
            entry = cache["entries"].get((driver, node))
            if entry is None:
                return f"no ResourceSlice for driver {driver}"
            matched = self._matching_devices(cache, driver, node, plan_key, plan)
            if not matched:
                return (f"no untainted device matches request "
                        f"{req.name or req.device_class_name!r}")
            want = len(matched) if req.allocation_mode == "All" else req.count
            if len(matched) < want:
                return (f"only {len(matched)}/{want} matching devices for "
                        f"request {req.name or req.device_class_name!r}")
            if not self._node_feasible(cache, node, req, driver, plan_key,
                                       plan, consumed):
                return (f"insufficient free capacity for request "
                        f"{req.name or req.device_class_name!r} "
                        f"(devices held by existing allocations)")
        return "infeasible"

    def _node_feasible(self, cache: dict, node: str, req, driver: str,
                       plan_key, plan: _MatchPlan, consumed) -> bool:
        entry = cache["entries"].get((driver, node))
        if entry is None:
            return False
        matched = self._matching_devices(cache, driver, node, plan_key, plan)
        if not matched:
            return False
        want = len(matched) if req.allocation_mode == "All" else req.count
        if len(matched) < want:
            return False
        if consumed is None:
            return True  # nothing consumed: matching count is the answer
        fit = 0
        for d in matched:
            if self._dev_fits_base(d, entry["caps"], consumed):
                fit += 1
                if fit >= want:
                    return True
        return fit >= want

    # -- allocation -----------------------------------------------------------

    def _class_info(self, class_name: str):
        snap = self._pass_snapshot
        if snap is not None and class_name in snap["classes"]:
            return snap["classes"][class_name]
        dc = self.api.try_get(DEVICE_CLASS, class_name)
        if dc is None:
            raise AllocationError(f"DeviceClass {class_name!r} not found")
        info = (dc.driver, getattr(dc, "match_attributes", {}),
                getattr(dc, "cel_selectors", []))
        if snap is not None:
            snap["classes"][class_name] = info
        return info

    def _match_plan(self, req) -> Tuple[str, _MatchPlan]:
        """(driver, compiled plan) for one request — class lookup, legacy
        selector parsing, and CEL compilation all happen here, once per
        request, not once per candidate device. Inside a pass, plans are
        additionally cached by content (class + selectors), so probing one
        pod's claim across 64 candidate nodes compiles its plan once."""
        snap = self._pass_snapshot
        key = None
        if snap is not None:
            key = (req.device_class_name, tuple(req.selectors),
                   tuple(getattr(req, "cel_selectors", ())))
            cached = snap["plans"].get(key)
            if cached is not None:
                snap["stats"]["plans_cached"] += 1
                return cached
        driver, match_attrs, cel_sels = self._class_info(req.device_class_name)
        all_cel = list(cel_sels) + list(getattr(req, "cel_selectors", ()))
        plan = (driver, _MatchPlan(driver, match_attrs, req.selectors, all_cel))
        if snap is not None:
            snap["plans"][key] = plan
            snap["stats"]["plans_compiled"] += 1
        return plan

    def _used_mask(self, node_name: str, in_flight: Sequence = ()) -> int:
        """Chip-bitmask of allocated chips on one node: the incrementally
        maintained pass mask plus any in-flight sibling allocations; a
        from-scratch scan outside a pass."""
        snap = self._pass_snapshot
        if snap is not None:
            base = snap["used_masks"].get(node_name, 0)
            index = snap["index"]
        else:
            index = self._device_index(self._list_slices())
            masks: Dict[str, int] = {}
            for alloc in self._list_allocations():
                self._accrue_mask(masks, index, alloc, +1)
            base = masks.get(node_name, 0)
        flight = [a for a in in_flight
                  if a is not None and a.node_name == node_name]
        if flight:
            overlay = {node_name: base}
            for alloc in flight:
                self._accrue_mask(overlay, index, alloc, +1)
            base = overlay[node_name]
        return base

    def _rank_candidates(self, driver: str, node_name: str, candidates,
                         used_mask: int):
        """Fragmentation-scored best-fit order for one request's candidate
        devices: fewest surviving larger-profile placements destroyed
        first (name tie-break keeps it deterministic). Returns the ordered
        list plus {device name -> (score, chip bits)} so the chosen loop
        can maintain the pending mask and observe the score histogram.
        Nodes without placement tables keep slice order (score None)."""
        cache = self._feasibility_state()
        entry = cache["entries"].get((driver, node_name))
        tables = entry.get("tables") if entry else None
        if tables is None:
            return candidates, {}
        surviving = tables.surviving(used_mask, entry["available"])
        scores: Dict[str, tuple] = {}
        for d in candidates:
            bits = entry["dev_mask"].get(d.name)
            if bits is None:
                bits = placement.chip_bits_of_device(d)
            scores[d.name] = (tables.frag_score(bits, surviving), bits)
        candidates = sorted(
            candidates, key=lambda d: (scores[d.name][0], d.name))
        return candidates, scores

    def allocate_on_node(self, claim: ResourceClaim, node_name: str,
                         in_flight: Sequence = ()) -> Optional[AllocationResult]:
        """Try to satisfy every request of the claim on one node; returns the
        allocation or None when it doesn't fit. ``in_flight``: allocations
        computed this pass but not yet written (sibling claims of the same
        pod) — their devices count as consumed.

        With ``best_fit`` (the default), candidates within a request are
        probed in fragmentation-score order — the placement that destroys
        the fewest surviving larger-profile placements wins — instead of
        raw slice order; `_fits` stays the authority on whether a device
        can actually be taken (counter semantics are unchanged, only the
        preference order moved)."""
        snap = self._pass_snapshot
        if snap is not None:
            snap["stats"]["nodes_probed"] += 1
            # Per-pass device view, indexed once in begin_pass — not
            # re-listed and re-grouped on every node probe.
            slices_by_driver = snap["slices_by_node"].get(node_name, {})
        else:
            slices_by_driver = {
                s.driver: s
                for s in self._list_slices()
                if s.node_name == node_name
            }
        consumed = self._consumed_for_node(node_name, in_flight)
        # Chip-mask view of the same state, for placement scoring only.
        # Scoring needs the static feasibility index; without a kind
        # fingerprint that index can never cache, so ranking would rebuild
        # it on EVERY probe — skip scoring there (ordering is a
        # preference; counter semantics are unchanged either way).
        score_placements = self.best_fit and (
            getattr(self.api, "kind_fingerprint", None) is not None)
        used_mask = self._used_mask(node_name, in_flight) if score_placements else 0
        pending: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
        pending_mask = 0
        # Scores are buffered on the result and observed at commit():
        # failed probes and successful-but-abandoned probes (a sibling
        # claim failed on the node) were never "chosen".
        chosen_scores: List[float] = []
        picked: List[DeviceRequestAllocationResult] = []
        picked_names: set = set()
        for req in claim.requests:
            driver, plan = self._match_plan(req)
            rs = slices_by_driver.get(driver)
            if rs is None:
                return None
            candidates = [
                d for d in rs.devices
                if d.name not in picked_names
                and not any(t.effect in ("NoSchedule", "NoExecute") for t in d.taints)
                and plan.matches(d)
            ]
            scores: Dict[str, tuple] = {}
            if score_placements:
                candidates, scores = self._rank_candidates(
                    driver, node_name, candidates, used_mask | pending_mask)
            want = len(candidates) if req.allocation_mode == "All" else req.count
            chosen: List[Device] = []
            for dev in candidates:
                if len(chosen) >= want:
                    break
                if self._fits(rs, dev, consumed, pending):
                    chosen.append(dev)
                    for cc in dev.consumes_counters:
                        for cname, ctr in cc.counters.items():
                            pending[cc.counter_set][cname] += ctr.value
                    got = scores.get(dev.name)
                    if got is not None:
                        pending_mask |= got[1]
                        chosen_scores.append(float(got[0]))
            if len(chosen) < want or (req.allocation_mode == "All" and not chosen):
                return None
            for dev in chosen:
                picked_names.add(dev.name)
                picked.append(
                    DeviceRequestAllocationResult(
                        request=req.name, driver=driver,
                        pool=rs.pool.name, device=dev.name,
                    )
                )
        result = AllocationResult(devices=picked, node_name=node_name)
        if chosen_scores and self._pass_snapshot is not None:
            # Observed at commit(), never here: a successful probe the
            # caller then abandons (a sibling claim failed on this node,
            # or an outside-a-pass probe that is never committed) was not
            # "chosen", and the same claim re-probed elsewhere must not
            # double-count.
            self._pass_snapshot["pending_scores"][id(result)] = (
                result, chosen_scores)
        return result
