"""SimCluster: nodes with real plugins + emulated scheduler/kubelet/DaemonSet.

Composes everything into a runnable in-process cluster:

- N "TPU hosts", each with a real TpuDriver + ComputeDomainDriver over a
  MockTpuLib worker of one slice profile;
- the compute-domain Controller;
- a scheduler pass (claims from templates, structured-parameters
  allocation, node binding);
- a kubelet pass per node (Prepare via the real plugins, CDI env
  materialized onto the pod, retry on RetryableError);
- a DaemonSet controller pass (pods follow node labels), which also *runs*
  slice-agent pods as in-process SliceAgents — the container the DaemonSet
  would start.

Deterministic by design: drive with ``step()`` until convergence instead of
background threads, so e2e tests never race.

Event-driven by design too: every pass feeds off the API server's watch
stream. Events drain into per-pass dirty sets, so the scheduler reconciles
only pods that changed (plus an unschedulable backlog retried on capacity
events), the kubelet only pods with node-side work outstanding, and the
DaemonSet/GC/chaos passes skip entirely when nothing they react to moved —
a quiet cluster steps in O(1), not O(objects). ``settle()``/``wait_for()``
detect that quiescence through the store's O(1) kind fingerprints instead
of re-listing every pod per step.
"""

from __future__ import annotations

import logging
import os
import queue
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from k8s_dra_driver_tpu.api.configs import (
    COMPUTE_DOMAIN_DRIVER_NAME,
    TPU_DRIVER_NAME,
    channel_domain_uid,
)
from k8s_dra_driver_tpu.controller import Controller
from k8s_dra_driver_tpu.controller.templates import (
    DEVICE_CLASS_CHANNEL,
    DEVICE_CLASS_DAEMON,
    DEVICE_CLASS_TPU,
)
from k8s_dra_driver_tpu.daemon import SliceAgent
from k8s_dra_driver_tpu.k8s import APIServer, NotFoundError, WatchEvent
from k8s_dra_driver_tpu.k8s.store import _match_labels as _store_match_labels
from k8s_dra_driver_tpu.k8s.informer import INFORMER_WATCH_QUEUE_MAXSIZE
from k8s_dra_driver_tpu.k8s.conditions import (
    CONDITION_FALSE,
    CONDITION_TRUE,
    get_condition,
    set_condition,
)
from k8s_dra_driver_tpu.k8s.objects import AlreadyExistsError
from k8s_dra_driver_tpu.k8s.core import (
    CLAIM_COND_ALLOCATED,
    CLAIM_COND_PREPARED,
    COMPUTE_DOMAIN,
    COMPUTE_DOMAIN_CLIQUE,
    DAEMON_SET,
    DEVICE_CLASS,
    DeviceClass,
    NODE,
    Node,
    POD,
    Pod,
    ObjectReference,
    RESOURCE_CLAIM,
    RESOURCE_CLAIM_TEMPLATE,
    RESOURCE_SLICE,
    ResourceClaim,
)
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.api.computedomain import ComputeDomainPlacement
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg import placement as placement_lib
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_ALLOCATION_FAILED,
    REASON_DOMAIN_PLACED,
    REASON_FAILED_SCHEDULING,
    REASON_SCHEDULED,
)
from k8s_dra_driver_tpu.pkg.history import (
    HistoryStore,
    RULE_SCHED_BIND,
    RULE_SCHED_PARK,
)
from k8s_dra_driver_tpu.pkg.lifecycle import ClaimLifecycleAnalyzer
from k8s_dra_driver_tpu.pkg.metrics import Registry
from k8s_dra_driver_tpu.plugins.checkpoint import PREPARE_ABORTED
from k8s_dra_driver_tpu.plugins.computedomain.computedomain import RetryableError
from k8s_dra_driver_tpu.plugins.computedomain.driver import ComputeDomainDriver
from k8s_dra_driver_tpu.plugins.tpu.driver import TpuDriver
from k8s_dra_driver_tpu.sim.allocator import AllocationError, Allocator
from k8s_dra_driver_tpu.tpulib import ChipHealth, MockTpuLib

log = logging.getLogger(__name__)

DRIVER_NAMESPACE = "tpu-dra-driver"
DEVICE_CLASS_SUBSLICE = "subslice.tpu.google.com"
DEVICE_CLASS_VFIO = "vfio.tpu.google.com"

# Node annotation consumed by the chaos pass: "0=unhealthy,2=healthy" flips
# per-chip mock health so kubectl-driven suites can exercise the
# taint/republish chain without reaching into the process (the shell-tier
# stand-in for the reference's fault-injection bats scenarios,
# /root/reference/tests/bats/test_gpu_robustness.bats).
CHAOS_CHIP_HEALTH_ANNOTATION = "sim.tpu.google.com/chip-health"
# Same idea for ICI links: "0-1=unhealthy,2-3=healthy" flips the mock
# link between two host-local chips, driving the link-taint / DeviceDegraded
# / DomainDegraded chain from outside the process.
CHAOS_LINK_HEALTH_ANNOTATION = "sim.tpu.google.com/link-health"
# Synthetic load: the annotation value is a tpulib.loadtrace spec
# ("bursty:seed=3,period=60", "constant:level=0.99", ...) installed into
# the node's mock tpulib — prepared chips then follow the trace, and the
# telemetry plane (sampler -> rollup -> SLO) sees realistic utilization
# without hardware. Empty value clears the trace.
CHAOS_LOAD_TRACE_ANNOTATION = "sim.tpu.google.com/load-trace"
# Sustained ICI error injection: "0-1=50" drives 50 errors/s onto the
# link between chips 0 and 1 — the telemetry sampler's error-rate
# threshold must degrade exactly the spanning devices via the existing
# taint chain. "0-1=0" clears.
CHAOS_LINK_ERRORS_ANNOTATION = "sim.tpu.google.com/link-errors"
# Host failure: "true" hard-kills the node's slice agents (no dying-gasp
# API writes — their liveness leases simply stop renewing and expire),
# marks the node unreachable (kubelet/scheduler/GC/plugin-resolver all
# skip it), and so drives the ElasticComputeDomains heal path from
# outside the process. Clearing the annotation "returns" the host: its
# agents restart, re-join their cliques (same worker slot), and the
# domain grows back.
CHAOS_NODE_DOWN_ANNOTATION = "sim.tpu.google.com/node-down"

# Comma-list env keys whose values union when a pod holds several claims
# (each claim's CDI spec names only its own chips).
UNION_ENV_KEYS = {"TPU_VISIBLE_CHIPS", "TPU_VISIBLE_DEVICES"}

# Kinds whose watch streams drive the dirty sets. RESOURCE_SLICE /
# RESOURCE_CLAIM_TEMPLATE / DEVICE_CLASS events carry no per-object work of
# their own but mean previously-unschedulable pods may now fit;
# TenantQuota edits (a raised chip quota, a weight change) do too.
_WATCHED_KINDS = (POD, RESOURCE_CLAIM, DAEMON_SET, NODE, RESOURCE_SLICE,
                  RESOURCE_CLAIM_TEMPLATE, DEVICE_CLASS, "TenantQuota")

# Kinds whose fingerprints define "nothing is moving" for settle()/
# wait_for(): everything the control loops read or write.
_QUIESCENCE_KINDS = (POD, RESOURCE_CLAIM, DAEMON_SET, NODE, RESOURCE_SLICE,
                     RESOURCE_CLAIM_TEMPLATE, COMPUTE_DOMAIN,
                     COMPUTE_DOMAIN_CLIQUE, "ServingGroup", "TenantQuota")

_PodKey = Tuple[str, str]  # (namespace, name)


class _PassAdmission:
    """One shared admission snapshot per scheduler pass: the whole dirty
    Pending batch admits against it instead of recomputing per pod.

    - ``feasible``: claim-shape -> the ordered candidate list one
      ``feasible_nodes`` call produced. Capacity only shrinks during a
      pass (allocations commit, never release), so a cached list stays a
      valid SUPERSET of the truly feasible nodes: stale entries cost a
      cheap failed probe, after which ``prune`` drops them so sibling
      pods of the same shape stop re-probing (the storm case: thousands
      of identical single-chip claims resolve against ONE feasibility
      computation per pass). Because pruning is heuristic for multi-claim
      pods (a joint-sibling failure is pod-specific), a pod is only
      parked unschedulable after a FRESH recompute confirms it.
    - ``domains``: ComputeDomain-by-uid cache so a gang of domain workers
      resolves its domain (and its recorded host-grid block) once per
      pass instead of listing ComputeDomains per worker — the gang
      places in one pass with one block computation.
    """

    __slots__ = ("feasible", "domains")

    def __init__(self) -> None:
        self.feasible: Dict[tuple, List[str]] = {}
        self.domains: Dict[str, object] = {}

    @staticmethod
    def shape_of(claims) -> tuple:
        """Feasibility-relevant identity of a claim set: feasible_nodes()
        depends only on the requests' class/selectors/count/mode (plus
        cluster state shared across the pass), never on claim names."""
        return tuple(
            (req.device_class_name, tuple(req.selectors),
             tuple(getattr(req, "cel_selectors", ())), req.count,
             req.allocation_mode)
            for c in claims for req in c.requests
        )

    def prune(self, shape: tuple, node: str) -> None:
        """Drop a node whose probe failed from the shape's cached list —
        mid-pass capacity never comes back, so it cannot turn feasible
        again for this shape before the next pass."""
        cached = self.feasible.get(shape)
        if cached is not None:
            try:
                cached.remove(node)
            except ValueError:
                pass


@dataclass
class SimNode:
    name: str
    tpulib: MockTpuLib
    tpu_driver: TpuDriver
    cd_driver: ComputeDomainDriver
    agents: Dict[str, SliceAgent] = field(default_factory=dict)  # pod name -> agent


class SimCluster:
    def __init__(
        self,
        workdir: str,
        profile: str = "v5e-16",
        num_hosts: Optional[int] = None,
        gates: str = "",
        api: Optional[APIServer] = None,
        loopback_agents: bool = False,
        metrics_registry: Optional[Registry] = None,
        rebalancer_config=None,
        persist_dir: Optional[str] = None,
        elastic_config=None,
        contention_config=None,
        preemption_config=None,
    ):
        """``loopback_agents=True`` registers slice agents with their real
        harness address (127.0.0.1 — everything runs in this process), so
        the bootstrap env the CDI specs inject is genuinely dialable and a
        test can launch actual OS processes from it (the
        multi-process collective proof). Combine with
        ``SliceAgentsWithDNSNames=false`` so clique members publish the
        raw address instead of sim-only DNS names."""
        self.gates = fg.parse(gates)
        self._history_dir = None
        if api is None and (persist_dir is not None
                            or self.gates.enabled("StorePersistence")):
            # WAL+snapshot-backed store: a restarted sim replays the
            # previous run's state instead of re-running its storm. The
            # bootstrap below tolerates already-present Nodes/classes.
            from k8s_dra_driver_tpu.k8s.persist import open_persistent_store

            store_dir = persist_dir or os.path.join(workdir, "store")
            api = open_persistent_store(store_dir)
            # Flight-recorder history persists beside the store WAL, so a
            # restarted sim keeps the fleet's telemetry past and every
            # pre-restart DecisionRecord `explain` needs.
            self._history_dir = os.path.join(store_dir, "history")
            if self.gates.enabled("FederatedFleet"):
                # Leader half of WAL-streamed replication: followers in
                # other clusters tail this store's WAL (federation/).
                # The HTTPAPIServer probes exactly this attribute to
                # serve the /replication routes.
                from k8s_dra_driver_tpu.federation import ReplicationSource

                api.replication = ReplicationSource(api)
        self.api = api if api is not None else APIServer()
        self.workdir = workdir
        self.loopback_agents = loopback_agents
        # One cluster-wide registry: every node plugin, the controller,
        # and the allocator expose on it (per-node series merge — the
        # sim's /metrics reads as a cluster aggregate).
        self.metrics_registry = metrics_registry or Registry()
        if hasattr(self.api, "attach_metrics"):
            self.api.attach_metrics(self.metrics_registry)
        repl = getattr(self.api, "replication", None)
        if repl is not None:
            repl.attach_metrics(self.metrics_registry)
        # Flight recorder (pkg/history.py): always on like tracing —
        # controllers write DecisionRecords through it, the telemetry
        # plane pushes series into its downsample tiers, and
        # `tpu-kubectl explain` / the future forecaster+recommender read
        # it back. Persistent only when the store itself persists.
        self.history = HistoryStore(
            self._history_dir, metrics_registry=self.metrics_registry,
            clock=lambda: self.sim_time)
        # In-process query seam: explain/top reach history through the
        # api handle (remote clients get the same attribute from
        # RemoteAPIServer over /history/*).
        self.api.history = self.history
        # Critical-path profiler: watch-fed (zero steady-state lists),
        # feeds the tpu_dra_lifecycle_phase_seconds histogram, the
        # lifecycle-phase/* history series, a lifecycle/claim-profiled
        # DecisionRecord per completed claim, and the quantized
        # observedFootprint status write. Exposed on the api handle so
        # `explain --latency` finds it next to history.
        self.lifecycle = ClaimLifecycleAnalyzer(
            self.api, history=self.history,
            metrics_registry=self.metrics_registry)
        self.api.lifecycle = self.lifecycle
        # Span-loss accounting for the process-default tracer rides the
        # cluster registry (idempotent across clusters in one process).
        tracing.get_tracer().attach_metrics(self.metrics_registry)
        self.allocator = Allocator(self.api,
                                   metrics_registry=self.metrics_registry)
        # Event plane: the emulated scheduler and the allocator verdicts
        # narrate through the same correlator the real actors use.
        self.sched_recorder = EventRecorder(
            self.api, "scheduler", metrics_registry=self.metrics_registry)
        self.alloc_recorder = EventRecorder(
            self.api, "allocator", metrics_registry=self.metrics_registry)
        self.profile = profile
        self.nodes: Dict[str, SimNode] = {}
        self._chaos_applied: Dict[str, str] = {}  # node -> last annotation value
        self._chaos_link_applied: Dict[str, str] = {}
        self._chaos_trace_applied: Dict[str, str] = {}
        self._chaos_link_err_applied: Dict[str, str] = {}
        self._chaos_down_applied: Dict[str, str] = {}
        # Hosts currently failed by the node-down chaos annotation: their
        # plugins resolve to None, the kubelet/GC/agent passes skip them,
        # and the scheduler never places onto them — the in-process
        # approximation of a machine that stopped answering.
        self._down_nodes: Set[str] = set()
        self._gc_prev_claim_uids: set = set()
        # Virtual wall clock: one second per step, independent of the
        # telemetry gate — slice-agent liveness leases and the resize
        # orchestrator's backoff/stall timers run on it, so failure
        # detection and heal latency are deterministic per seed.
        self.sim_time = 0.0
        self.sim_dt = 1.0
        # Sim-tier agent leases expire fast (3 virtual seconds) so a heal
        # starts within a few steps of a kill; real deployments keep the
        # 30s default.
        self.agent_lease_s = 3.0
        # -- fleet telemetry (FleetTelemetry gate) --------------------------
        # The sim drives sampling synchronously on a virtual clock
        # (telemetry_clock advances telemetry_dt per step), so traces,
        # window stats, and SLO burn rates are deterministic per seed —
        # no wall-clock dependence anywhere in the pipeline.
        self.telemetry = None
        self.slo = None
        self.telemetry_clock = 0.0
        self.telemetry_dt = 1.0
        self._pods_seen_running: Set[str] = set()
        # uid -> telemetry_clock at first sight: time-to-running is
        # measured on the VIRTUAL clock (ticks a pod waited), never
        # wall time — the telemetry pipeline's determinism contract.
        self._pod_first_seen_tick: Dict[str, float] = {}
        if self.gates.enabled("FleetTelemetry"):
            from k8s_dra_driver_tpu.pkg.slo import SLOEvaluator, SLObjective
            from k8s_dra_driver_tpu.pkg.telemetry import TelemetryAggregator

            self.telemetry_recorder = EventRecorder(
                self.api, "telemetry", metrics_registry=self.metrics_registry)
            self.telemetry = TelemetryAggregator(
                self.api, self.metrics_registry)
            self.telemetry.history = self.history
            self.slo = SLOEvaluator(self.metrics_registry,
                                    recorder=self.telemetry_recorder)
            self.slo.history = self.history
            # Recording rules sized to the virtual second; tests/operators
            # replace them via slo.add() before the first step.
            self.slo.add(SLObjective(
                name="claim-duty-cycle",
                description="claim window duty-cycle p95 below overload",
                target=0.90, bound=0.95, op="gt",
                windows=((60.0, 15.0), (240.0, 60.0))))
            self.slo.add(SLObjective(
                name="domain-ici-utilization",
                description="domain ICI utilization p95 below saturation",
                target=0.90, bound=0.90, op="gt",
                windows=((60.0, 15.0), (240.0, 60.0))))
            self.slo.add(SLObjective(
                name="scheduler-time-to-running",
                description="pod time-to-running under the serving bound",
                target=0.95, bound=30.0, op="gt",
                windows=((120.0, 30.0),)))
        # -- serving loop (ServingAutoscaler gate, requires FleetTelemetry):
        # traffic engine (sensing) + ServingGroup controller (actuation),
        # both driven synchronously off the telemetry tick.
        self.serving = None
        self.autoscaler = None
        if self.gates.enabled("ServingAutoscaler"):
            # Dependency check up front: the loop is meaningless without
            # the telemetry plane it closes on.
            fg.validate_feature_gates(self.gates)
            from k8s_dra_driver_tpu.autoscaler import (
                ServingGroupController,
                TrafficEngine,
            )

            self.serving = TrafficEngine(
                self.api, self.metrics_registry, self.slo,
                claim_load_sink=self._install_claim_load)
            self.autoscaler = ServingGroupController(
                self.api, self.metrics_registry, self.serving,
                recorder=EventRecorder(
                    self.api, "autoscaler",
                    metrics_registry=self.metrics_registry))
        # -- dirty-set state fed by the watch streams -----------------------
        # Subscribed before any object is created below, so the cluster's
        # own bootstrap (nodes, device classes, published slices) arrives
        # as ordinary events; a pre-seeded api is covered by the one-shot
        # bootstrap scan on the first pass. The control loops drain every
        # pass but their POD dirty-keys are loss-sensitive, so these
        # watchers get a much deeper bound than the store default (a
        # 512-node storm boots >1024 slice events before the first drain).
        self._watch_queues: Dict[str, "queue.Queue[WatchEvent]"] = {
            kind: self.api.watch(kind, maxsize=INFORMER_WATCH_QUEUE_MAXSIZE)
            for kind in _WATCHED_KINDS
        }
        self._sched_dirty: Set[_PodKey] = set()    # pods needing scheduling
        self._sched_backlog: Set[_PodKey] = set()  # unschedulable, awaiting capacity
        self._kubelet_dirty: Set[_PodKey] = set()  # bound pods not yet Running
        self._ds_dirty = True
        self._gc_dirty = True
        self._chaos_dirty = True
        self._gc_deleted_claim_uids: set = set()
        # (node, pod name) -> latest Pod, maintained straight from the
        # watch stream — the agent pass never re-lists pods to find its
        # containers.
        self._agent_pods: Dict[Tuple[str, str], Pod] = {}
        # Pass-scoped admission snapshot (shape-keyed feasibility + domain
        # cache); non-None only while a scheduler pass is running.
        self._admission: Optional[_PassAdmission] = None
        self._bootstrapped = False
        self.controller = Controller(
            self.api, driver_namespace=DRIVER_NAMESPACE, cleanup_interval_s=3600,
            metrics_registry=self.metrics_registry,
            # Loopback runs launch real OS processes from the injected env:
            # the jax.distributed coordinator binds the advertised port on
            # THIS host, so it must be allocated free at DS render instead
            # of the fixed default (which any unrelated process may hold).
            dynamic_coordinator_port=loopback_agents,
        )
        # Live repack: enabled by the LiveRepack gate (default policy) or an
        # explicit RebalancerConfig (tests/bench tune budgets and mode).
        self.rebalancer = None
        if rebalancer_config is not None or self.gates.enabled("LiveRepack"):
            from k8s_dra_driver_tpu.rebalancer import (
                RebalanceController,
                RebalancerConfig,
            )

            self.rebalancer = RebalanceController(
                api=self.api,
                allocator=self.allocator,
                plugin_resolver=self._resolve_tpu_plugin,
                config=rebalancer_config or RebalancerConfig(),
                metrics_registry=self.metrics_registry,
                # Virtual clock: token-bucket refill and per-unit retry
                # backoff advance one second per step, deterministically.
                clock=lambda: self.sim_time,
            )
        # Elastic ComputeDomains: resize-epoch orchestration, enabled by
        # the gate or an explicit ElasticConfig (tests tune lease grace,
        # backoff, and the stall timeout).
        self.elastic = None
        if (elastic_config is not None
                or self.gates.enabled("ElasticComputeDomains")):
            from k8s_dra_driver_tpu.controller.elastic import (
                ElasticConfig,
                ElasticDomainController,
            )

            self.elastic = ElasticDomainController(
                api=self.api,
                allocator=self.allocator,
                plugin_resolver=self._resolve_tpu_plugin,
                cd_plugin_resolver=self._resolve_cd_plugin,
                config=elastic_config or ElasticConfig(),
                metrics_registry=self.metrics_registry,
                clock=lambda: self.sim_time,
            )
        self._install_device_classes()
        lib_probe = MockTpuLib(profile, worker_id=0)
        self._profile_hosts = lib_probe.profile.num_hosts
        self._host_chips = len(lib_probe.enumerate().chips)
        n = num_hosts if num_hosts is not None else self._profile_hosts
        if n % self._profile_hosts:
            raise ValueError(
                f"num_hosts={n} must be a multiple of profile {profile!r}'s "
                f"host count ({self._profile_hosts}): partial slices would "
                f"advertise hosts that don't exist"
            )
        for w in range(n):
            self._add_node(f"tpu-node-{w}", w)
        # -- contention plane (ContentionPolicy gate / explicit configs):
        # WFQ admission ordering + per-tenant quotas in the scheduler
        # pass, plus the checkpoint-aware preemption engine. Constructed
        # last: the manager's chip costing needs the probed host size.
        self.contention = None
        self.preemption = None
        if (contention_config is not None or preemption_config is not None
                or self.gates.enabled("ContentionPolicy")):
            from k8s_dra_driver_tpu.scheduling import (
                ContentionManager,
                PreemptionController,
            )

            self.contention = ContentionManager(
                self.api, metrics_registry=self.metrics_registry,
                recorder=self.sched_recorder,
                config=contention_config,
                whole_host_chips=self._host_chips,
                clock=lambda: self.sim_time,
            )
            self.preemption = PreemptionController(
                api=self.api,
                allocator=self.allocator,
                plugin_resolver=self._resolve_tpu_plugin,
                manager=self.contention,
                config=preemption_config,
                metrics_registry=self.metrics_registry,
                clock=lambda: self.sim_time,
            )
        # Satellite loop closures wired once everything exists: the
        # elastic orchestrator's heal latency feeds the SLO plane, and
        # the serving autoscaler's multi-group scale-up apportions fleet
        # headroom by tenant weight instead of first-writer-wins.
        if self.elastic is not None and self.slo is not None:
            from k8s_dra_driver_tpu.pkg.slo import heal_time_objective

            self.slo.add(heal_time_objective())
            self.elastic.heal_observer = self._observe_heal
        if self.autoscaler is not None:
            self.autoscaler.headroom_fn = self._fleet_free_chips
            if self.contention is not None:
                self.autoscaler.tenant_weight_fn = self.contention.weight_for
        # Decision provenance: every acting controller records through
        # the one flight recorder, so `explain` merges them all.
        for actor in (self.autoscaler, self.rebalancer, self.elastic,
                      self.contention, self.preemption):
            if actor is not None:
                actor.history = self.history

    # -- bootstrap -------------------------------------------------------------

    def _install_device_classes(self) -> None:
        # The CEL expressions are the same strings the Helm chart ships
        # (templates/deviceclasses.yaml) and are what actually gates
        # matching — the allocator evaluates them via k8s.celmini, so a
        # selector typo in the chart fails the sim e2e, not just a live
        # cluster (test_helm_chart pins chart<->sim expression parity).
        for name, driver, dev_type in (
            (DEVICE_CLASS_TPU, TPU_DRIVER_NAME, "tpu"),
            (DEVICE_CLASS_SUBSLICE, TPU_DRIVER_NAME, "subslice"),
            (DEVICE_CLASS_VFIO, TPU_DRIVER_NAME, "vfio"),
            (DEVICE_CLASS_CHANNEL, COMPUTE_DOMAIN_DRIVER_NAME, "channel"),
            (DEVICE_CLASS_DAEMON, COMPUTE_DOMAIN_DRIVER_NAME, "daemon"),
        ):
            # Domain-qualified attribute access: real DRA CEL exposes a
            # device's attributes as attributes["<driver domain>"].<name>
            # (the reference's expressions use the same form); celmini
            # resolves the qualified key against the flat map.
            expr = (f'device.driver == "{driver}" && '
                    f'device.attributes["{driver}"].type == "{dev_type}"')
            try:
                self.api.create(DeviceClass(
                    meta=new_meta(name), driver=driver,
                    cel_selectors=[expr],
                ))
            except AlreadyExistsError:
                pass  # attaching to a server that was already seeded

    def _add_node(self, name: str, worker_id: int) -> None:
        try:
            self.api.create(Node(meta=new_meta(name)))
        except AlreadyExistsError:
            pass  # restored/pre-seeded store already holds this node
        # --num-hosts beyond the profile's host count models additional
        # independent slices (a GKE node pool of several pod slices): node
        # w is host w % H of slice w // H, each slice with its own ICI
        # domain uid.
        slice_idx, host_idx = divmod(worker_id, self._profile_hosts)
        lib = MockTpuLib(
            self.profile, worker_id=host_idx,
            slice_uid=(None if slice_idx == 0
                       else f"mock-slice-{self.profile}.{slice_idx}"),
        )
        base = os.path.join(self.workdir, name)
        vfio_mgr = None
        if self.gates.enabled("PassthroughSupport"):
            # Per-node VFIO sysfs fixture (PCI addresses repeat across
            # hosts, so the tree cannot be shared) — the mock-NVML-style
            # seam the vfio rebind path runs against in CPU-only CI.
            from k8s_dra_driver_tpu.plugins.tpu.vfio import VfioPciManager
            from k8s_dra_driver_tpu.plugins.tpu.vfiosysfs import build_vfio_sysfs

            sys_root = os.path.join(base, "sysfs")
            dev_root = os.path.join(base, "dev")
            # iommufd present: the 'auto' backend prefers the per-device
            # cdev, and the explicit modes are both exercisable.
            build_vfio_sysfs(sys_root, dev_root, lib.enumerate().chips,
                             with_iommufd=True)
            vfio_mgr = VfioPciManager(sysfs_root=sys_root, dev_root=dev_root,
                                      fixture_kernel=True)
        tpu = TpuDriver(
            api=self.api, node_name=name, tpulib=lib,
            plugin_dir=os.path.join(base, "tpu-plugin"),
            cdi_root=os.path.join(base, "cdi"),
            gates=self.gates,
            vfio=vfio_mgr,
            metrics_registry=self.metrics_registry,
            # No per-plugin cleanup timer threads in the sim: at 8192
            # nodes that would be 16k threads (the container's PID cap
            # kills the process long before memory runs out) and the
            # sim's event-driven _gc_pass performs the same stale sweep
            # deterministically.
            cleanup_interval_s=0,
        )
        cd = ComputeDomainDriver(
            api=self.api, node_name=name, tpulib=lib,
            plugin_dir=os.path.join(base, "cd-plugin"),
            cdi_root=os.path.join(base, "cdi"),
            gates=self.gates,
            metrics_registry=self.metrics_registry,
        )
        tpu.start()
        cd.start(cleanup_interval_s=0)
        self.nodes[name] = SimNode(name=name, tpulib=lib, tpu_driver=tpu, cd_driver=cd)

    def start(self) -> None:
        self.controller.start()

    def stop(self) -> None:
        for node in self.nodes.values():
            for agent in node.agents.values():
                agent.shutdown()
            node.tpu_driver.shutdown()
            node.cd_driver.shutdown()
        if self.serving is not None:
            self.serving.close()
        if self.telemetry is not None:
            self.telemetry.close()
        self.controller.stop()
        for kind, q in self._watch_queues.items():
            self.api.stop_watch(kind, q)
        # Fold the flight recorder's segments into one snapshot so the
        # next run restores history from a single decode.
        self.history.close()
        wal = getattr(self.api, "_wal", None)
        if wal is not None:
            # Final compaction: the next run restores from one snapshot
            # decode instead of a long record replay.
            wal.compact(self.api)
            wal.close()

    # -- event ingestion ---------------------------------------------------------

    def _drain_events(self) -> None:
        """Move pending watch events into the per-pass dirty sets. Called
        at the top of every pass so work created earlier in the same step
        (a DS-created pod, a bind) is visible to the next pass without
        waiting a whole step."""
        if not self._bootstrapped:
            self._bootstrap_dirty()
        # Kick the store's off-lock fan-out: if another thread (controller,
        # plugin pool) enqueued events and was descheduled mid-dispatch,
        # this drain becomes the dispatcher instead of missing them.
        flush = getattr(self.api, "flush_watchers", None)
        if flush is not None:
            flush()
        for kind, q in self._watch_queues.items():
            while True:
                try:
                    ev = q.get_nowait()
                except queue.Empty:
                    break
                self._ingest(kind, ev)

    def _bootstrap_dirty(self) -> None:
        """One-shot full scan covering objects that existed before our
        watches (a pre-seeded api passed into __init__)."""
        self._bootstrapped = True
        for pod in self.api.list(POD):
            key = (pod.namespace, pod.meta.name)
            if pod.phase == "Pending":
                self._sched_dirty.add(key)
            if pod.node_name and pod.phase not in ("Running", "Failed"):
                self._kubelet_dirty.add(key)
            if self._is_agent_pod(pod):
                self._agent_pods[(pod.node_name, pod.meta.name)] = pod

    @staticmethod
    def _is_agent_pod(pod: Pod) -> bool:
        return any(c.command and c.command[0] == "compute-domain-daemon"
                   for c in pod.containers)

    def _ingest(self, kind: str, ev: WatchEvent) -> None:
        obj = ev.obj
        if kind == POD:
            key = (obj.meta.namespace, obj.meta.name)
            self._ds_dirty = True          # ownership / ready counts moved
            if self._is_agent_pod(obj):
                akey = (obj.node_name, obj.meta.name)
                if ev.type == "DELETED":
                    self._agent_pods.pop(akey, None)
                else:
                    self._agent_pods[akey] = obj
            if ev.type == "DELETED":
                self._gc_dirty = True      # consumers / owned claims to drop
                self._sched_dirty.discard(key)
                self._sched_backlog.discard(key)
                self._kubelet_dirty.discard(key)
                self._pods_seen_running.discard(obj.uid)
                self._pod_first_seen_tick.pop(obj.uid, None)
                if self.contention is not None:
                    # Drop the WFQ aging clock: a deleted-then-recreated
                    # name must not inherit the old pod's starvation.
                    self.contention.note_gone(key)
                return
            if self.slo is not None:
                self._pod_first_seen_tick.setdefault(
                    obj.uid, self.telemetry_clock)
            if (self.slo is not None and obj.phase == "Running"
                    and obj.uid not in self._pods_seen_running):
                # SLO recording rule input: time-to-running straight off
                # the watch stream (one observation per pod lifetime),
                # measured in VIRTUAL seconds since the pod was first
                # seen — wall time would make seeded runs host-dependent.
                self._pods_seen_running.add(obj.uid)
                first = self._pod_first_seen_tick.pop(
                    obj.uid, self.telemetry_clock)
                latency = max(0.0, self.telemetry_clock - first)
                self.slo.observe(
                    "scheduler-time-to-running", self.telemetry_clock,
                    latency, subject=(obj.meta.namespace, obj.meta.name),
                    ref=ObjectReference(kind=POD, name=obj.meta.name,
                                        namespace=obj.meta.namespace,
                                        uid=obj.uid))
            if obj.phase == "Pending":
                self._sched_dirty.add(key)
            else:
                self._sched_dirty.discard(key)
                self._sched_backlog.discard(key)
                if self.contention is not None:
                    # Left Pending (bound/failed): the aging clock ends;
                    # a future requeue starts a fresh wait.
                    self.contention.note_gone(key)
            if obj.node_name and obj.phase not in ("Running", "Failed"):
                self._kubelet_dirty.add(key)
            elif obj.phase in ("Running", "Failed"):
                self._kubelet_dirty.discard(key)
        elif kind == RESOURCE_CLAIM:
            # Any claim movement can change GC's mind (ownerRefs, consumer
            # lists, allocations) and can free capacity for the backlog.
            self._gc_dirty = True
            self._retry_backlog()
            if ev.type == "DELETED":
                self._gc_deleted_claim_uids.add(obj.uid)
        elif kind == DAEMON_SET:
            self._ds_dirty = True
            if ev.type == "DELETED":
                self._gc_dirty = True
        elif kind == NODE:
            self._chaos_dirty = True
            self._ds_dirty = True
            self._retry_backlog()
        elif kind in (RESOURCE_SLICE, RESOURCE_CLAIM_TEMPLATE, DEVICE_CLASS,
                      "TenantQuota"):
            # Capacity / matching rules / tenant quotas changed:
            # unschedulable (incl. quota-parked) pods may now fit.
            self._retry_backlog()

    def _retry_backlog(self) -> None:
        if self._sched_backlog:
            self._sched_dirty |= self._sched_backlog
            self._sched_backlog.clear()

    # -- control loop passes ----------------------------------------------------

    def step(self) -> None:
        """One pass of every emulated control loop."""
        self.sim_time += self.sim_dt
        self.controller.drain(timeout=5)
        self._chaos_pass()
        self._gc_pass()
        self._daemonset_pass()
        self._scheduler_pass()
        self._agent_pass()
        self.controller.drain(timeout=5)
        self._kubelet_pass()
        self._elastic_pass()
        self._preemption_pass()
        self._rebalance_pass()
        self._telemetry_pass()
        self.lifecycle.step(self.sim_time)

    def _resolve_tpu_plugin(self, node_name: str):
        node = self.nodes.get(node_name)
        if node is None or node_name in self._down_nodes:
            return None  # unknown, or failed by node-down chaos
        return node.tpu_driver

    def _resolve_cd_plugin(self, node_name: str):
        node = self.nodes.get(node_name)
        if node is None or node_name in self._down_nodes:
            return None
        return node.cd_driver

    def _elastic_pass(self) -> None:
        """Resize-epoch orchestration, after the kubelet pass (quiesce and
        restart see settled claim state) and BEFORE the rebalancer, so a
        starting epoch's owner-tagged cordons land first when both want
        the same hosts."""
        if self.elastic is None:
            return
        try:
            self.elastic.step()
        except Exception:  # noqa: BLE001 — resize is best-effort per pass; a bad pass must not kill the sim
            log.exception("elastic pass failed")

    def _preemption_pass(self) -> None:
        """Checkpoint-aware preemption, after the elastic pass (a resize
        epoch's owner-tagged cordons land first when both want the same
        hosts) and BEFORE the rebalancer, so higher-tier demand evicts
        ahead of defrag migration over the same units (the cordon CAS
        arbitrates any overlap — tpusan's preempt-vs-rebalancer
        scenario). Disabled (None) unless the ContentionPolicy gate or
        an explicit config turned the contention plane on."""
        if self.preemption is None:
            return
        try:
            self.preemption.step()
        except Exception:  # noqa: BLE001 — preemption is best-effort per pass; a bad pass must not kill the sim
            log.exception("preemption pass failed")

    def _rebalance_pass(self) -> None:
        """Live repack, after the kubelet pass so migrations see settled
        claim/pod state and rebound pods are picked up next step. Disabled
        (None) unless the LiveRepack gate or an explicit config turned the
        rebalancer on."""
        if self.rebalancer is None:
            return
        try:
            self.rebalancer.step()
        except Exception:  # noqa: BLE001 — repack is best-effort; a bad pass must not kill the sim
            log.exception("rebalance pass failed")

    def _informer_backlog(self) -> int:
        """Watch events delivered but not yet consumed by informer
        threads (agents' single-pod informers, controller caches) — NOT
        counting the sim's own pass queues, which by design drain at the
        top of the next pass. Nonzero means some cache still lags the
        store, so the cluster cannot be quiescent regardless of what the
        kind fingerprints say."""
        backlog = getattr(self.api, "watch_backlog", None)
        if backlog is None:
            return 0
        own = sum(q.qsize() for q in self._watch_queues.values())
        return max(0, backlog() - own)

    def _yield_to_consumers(self, budget_s: float = 0.05) -> None:
        """Give informer consumer threads the GIL until their queues
        drain (bounded). The zero-copy store made steps fast enough that
        a whole settle loop can finish before the OS ever schedules an
        agent's informer thread — the step loop then reads a stale cache
        and declares quiescence while a delivered event sits unconsumed
        (the daemon keeps publishing ready=False off a pod snapshot one
        revision behind the store)."""
        if not self._informer_backlog():
            return
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            time.sleep(0.001)
            if not self._informer_backlog():
                return

    def _quiescence_token(self) -> tuple:
        """O(1) change-detection over every kind the control loops touch.
        Two steps with identical tokens mean the second step wrote nothing
        to the API — at that point further steps cannot make progress
        (every pass is a function of API state plus idempotent retries)."""
        fp = getattr(self.api, "kind_fingerprint", None)
        if fp is None:
            return (object(),)  # unknown backend: tokens never equal
        token = tuple(fp(kind) for kind in _QUIESCENCE_KINDS)
        # Backoff-paced retries are pending work that writes NOTHING until
        # the delay elapses: fold the virtual clock in while any are owed
        # so settle()/wait_for() keep stepping instead of declaring the
        # cluster quiet two steps before the retry fires.
        pending = 0
        if self.rebalancer is not None:
            pending += self.rebalancer.retry_backoff.pending()
        if self.preemption is not None:
            pending += self.preemption.retry_backoff.pending()
        if self.elastic is not None:
            # In-flight epochs and downed hosts are pending work too: a
            # lease quietly expiring, a bundle recompile, or a stall
            # timeout all need further steps to surface.
            pending += self.elastic.pending_retries()
            pending += self.elastic.in_flight
            pending += len(self._down_nodes)
        # Unconsumed watch deliveries are pending work in exactly the same
        # sense: the consumer thread will act on them, just hasn't run yet.
        pending += self._informer_backlog()
        if pending:
            token += (pending, int(self.sim_time))
        return token

    def settle(self, max_steps: int = 20) -> None:
        """Step until every pod reached a terminal-ish state, the cluster
        quiesced (two consecutive steps with no API writes — detected via
        the O(1) kind fingerprints), or the cap hit."""
        prev = None
        quiet = 0
        pods: List[Pod] = []
        pod_fp = None
        for _ in range(max_steps):
            self.step()
            self._yield_to_consumers()
            fp = getattr(self.api, "kind_fingerprint", None)
            cur_pod_fp = fp(POD) if fp else None
            if cur_pod_fp is None or cur_pod_fp != pod_fp:
                pods = self.api.list(POD)  # tpulint: disable=store-scan -- fingerprint-gated: re-lists only when the Pod kind actually changed, O(1) per step at quiescence
                pod_fp = cur_pod_fp
            if all(p.phase in ("Running", "Failed") for p in pods):
                return
            token = self._quiescence_token()
            quiet = quiet + 1 if token == prev else 0
            prev = token
            if quiet >= 2:
                return

    def wait_for(self, predicate, max_steps: int = 20) -> bool:
        """Step until predicate(self) holds. Pod phases settling does not
        imply the controllers' status writes have converged (they may trail
        by a pass), so status assertions should use this, not settle().
        Returns early once the cluster quiesces: if two consecutive steps
        changed nothing, stepping further cannot flip the predicate."""
        prev = None
        quiet = 0
        for _ in range(max_steps):
            if predicate(self):
                return True
            self.step()
            self._yield_to_consumers()
            token = self._quiescence_token()
            quiet = quiet + 1 if token == prev else 0
            prev = token
            if quiet >= 2:
                break
        return predicate(self)

    # -- DaemonSet controller ----------------------------------------------------

    def _daemonset_pass(self) -> None:
        self._drain_events()
        if not self._ds_dirty:
            return
        self._ds_dirty = False
        # Hoisted scans for the whole pass: nodes once (the old per-DS
        # label_selector list walked the full Node bucket anyway), pods
        # once per DISTINCT DS namespace through the PR 3 (kind, ns)
        # index — not cluster-wide, which would regress sims where
        # workload pods dwarf the DS namespaces (store-scan hygiene
        # without losing the index).
        all_nodes = self.api.list(NODE)
        daemonsets = self.api.list(DAEMON_SET)
        pods_by_ns: Dict[str, List[Pod]] = {
            ns: self.api.list(POD, namespace=ns)
            for ns in {ds.namespace for ds in daemonsets}
        }
        for ds in daemonsets:
            matching = [n for n in all_nodes
                        if _store_match_labels(n, ds.node_selector)]
            want = {n.name for n in matching}
            ns_pods = pods_by_ns.get(ds.namespace, [])
            have = {p.node_name: p for p in ns_pods if p.owned_by(ds)}
            for node_name in want - have.keys():
                pod = Pod(
                    meta=new_meta(
                        f"{ds.meta.name}-{node_name}", ds.namespace,
                        labels=dict(ds.template.labels),
                    ),
                    node_name=node_name,  # DS pods bypass the scheduler
                    containers=[c for c in ds.template.containers],
                    resource_claims=list(ds.template.resource_claims),
                )
                pod.add_owner(ds)
                self.api.create(pod)
            for node_name in have.keys() - want:
                pod = have[node_name]
                self._teardown_pod(pod)
                try:
                    self.api.delete(POD, pod.meta.name, pod.namespace)
                except NotFoundError:
                    pass
            # Ready count computed ONCE from the listing above — not
            # re-listed inside the mutation closure on every CAS retry.
            desired = len(want)
            ready = sum(1 for p in ns_pods
                        if p.owned_by(ds) and p.ready
                        and p.node_name in want)
            if ds.desired == desired and ds.ready == ready:
                continue  # no-op status write would just churn the watch

            def set_counts(obj, desired=desired, ready=ready):
                obj.desired = desired
                obj.ready = ready
            try:
                self.api.update_with_retry(DAEMON_SET, ds.meta.name, ds.namespace, set_counts)
            except NotFoundError:
                pass

    # -- scheduler ----------------------------------------------------------------

    def _ensure_claims_for_pod(self, pod: Pod) -> Dict[str, ResourceClaim]:
        claims: Dict[str, ResourceClaim] = {}
        for ref in pod.resource_claims:
            if ref.resource_claim_name:
                obj = self.api.try_get(RESOURCE_CLAIM, ref.resource_claim_name, pod.namespace)
                if obj is None:
                    raise AllocationError(
                        f"pod {pod.key}: claim {ref.resource_claim_name} missing"
                    )
            else:
                name = f"{pod.meta.name}-{ref.name}"
                obj = self.api.try_get(RESOURCE_CLAIM, name, pod.namespace)
                if obj is None:
                    rct = self.api.try_get(
                        RESOURCE_CLAIM_TEMPLATE, ref.resource_claim_template_name,
                        pod.namespace,
                    )
                    if rct is None:
                        raise AllocationError(
                            f"pod {pod.key}: RCT {ref.resource_claim_template_name} missing"
                        )
                    claim = ResourceClaim(
                        meta=new_meta(name, pod.namespace),
                        requests=list(rct.requests),
                        config=list(rct.config),
                    )
                    claim.add_owner(pod)
                    obj = self.api.create(claim)
            claims[ref.name] = obj  # type: ignore[assignment]
        return claims

    def _scheduler_pass(self) -> None:
        # One snapshot of slices + existing allocations per pass; every
        # allocation written during the pass is recorded via
        # allocator.commit(), so the snapshot cannot double-book.
        with tracing.span("scheduler.pass") as sp:
            self.allocator.begin_pass()
            self._admission = _PassAdmission()
            try:
                self._scheduler_pass_inner()
            finally:
                self._admission = None
                if self.contention is not None:
                    # Publish per-tenant gauges + change-gated
                    # TenantQuota status for whatever this pass admitted.
                    self.contention.end_pass()
                self.allocator.end_pass()
                # Per-pass allocator decisions ride on the span: nodes
                # probed, plans cached vs compiled, commits/rollbacks.
                sp.attrs.update(self.allocator.last_pass_stats)

    def _scheduler_pass_inner(self) -> None:
        self._drain_events()
        work, self._sched_dirty = self._sched_dirty, set()
        pending = self._admission_order(work)
        try:
            while pending:
                key = pending.pop(0)
                pod = self.api.try_get(POD, key[1], key[0])
                if pod is None or pod.phase != "Pending":
                    continue
                if self._schedule_pod(pod) == "unschedulable":
                    # Parked until a capacity event (claim/slice/node/
                    # template movement) promotes the backlog back into
                    # the dirty set.
                    self._sched_backlog.add(key)
        except BaseException:
            # A mid-pass crash (e.g. a CAS retry exhausting against a
            # concurrent controller) must not silently drop the pods we
            # drained but never reached — the old re-list-every-pass
            # scheduler self-healed; re-dirty them so the next pass does.
            self._sched_dirty.add(key)
            self._sched_dirty.update(pending)
            raise

    def _admission_order(self, work: Set[_PodKey]) -> List[_PodKey]:
        """Admission order for one dirty batch: plain sorted keys, or —
        with the contention plane on — weighted-fair-queuing order over
        tenant weights (aged-first, then tier, then virtual finish; see
        scheduling/wfq.py). Keys whose pod is gone or no longer Pending
        keep their sorted slot at the tail: the pass loop's own re-fetch
        discards them."""
        if self.contention is None or not work:
            return sorted(work)
        pods = []
        leftover = []
        for key in sorted(work):
            pod = self.api.try_get(POD, key[1], key[0])
            if pod is not None and pod.phase == "Pending":
                pods.append(pod)
            else:
                leftover.append(key)
        self.contention.begin_pass()
        ordered = self.contention.order(
            pods, now=self.sim_time, cost_of=self._pod_chip_cost,
            claims_of=self._pod_existing_claims)
        return ordered + leftover

    def _pod_existing_claims(self, pod: Pod) -> List[ResourceClaim]:
        """A pod's already-existing claims, read-only (generated claims
        that haven't been created yet simply don't contribute — the
        authoritative creation stays in _ensure_claims_for_pod)."""
        out: List[ResourceClaim] = []
        for ref in pod.resource_claims:
            name = ref.resource_claim_name or f"{pod.meta.name}-{ref.name}"
            obj = self.api.try_get(RESOURCE_CLAIM, name, pod.namespace)
            if obj is not None:
                out.append(obj)
        return out

    def _pod_chip_cost(self, pod: Pod) -> float:
        """WFQ service cost of one pending pod: chips across its claim
        refs, resolving generated claims' templates read-only."""
        from k8s_dra_driver_tpu.scheduling.tiers import claim_chip_cost

        total = 0.0
        for ref in pod.resource_claims:
            name = ref.resource_claim_name or f"{pod.meta.name}-{ref.name}"
            obj = self.api.try_get(RESOURCE_CLAIM, name, pod.namespace)
            if obj is None and ref.resource_claim_template_name:
                obj = self.api.try_get(
                    RESOURCE_CLAIM_TEMPLATE,
                    ref.resource_claim_template_name, pod.namespace)
            if obj is not None:
                total += claim_chip_cost(obj, self._host_chips)
        return total

    def _schedule_pod(self, pod: Pod) -> str:
        """Schedule one Pending pod; returns 'bound', 'unschedulable', or
        'failed'. Probes only allocator-feasible nodes, most-free-first;
        the exhaustive probe-every-node path remains available as the
        oracle the feasibility property tests diff against. Every verdict
        is narrated as an Event on the pod (and AllocationFailed on the
        claims), so `describe pod` answers "why is it Pending"."""
        try:
            claims = self._ensure_claims_for_pod(pod)
        except AllocationError as e:
            log.debug("pod %s: %s", pod.key, e)
            self.sched_recorder.warning(pod, REASON_FAILED_SCHEDULING, str(e))
            return "unschedulable"
        unallocated = [c for c in claims.values() if c.allocation is None]
        if self.contention is not None and unallocated:
            veto = self.contention.quota_veto(pod, list(claims.values()))
            if veto:
                # Parked by tenant policy, not capacity: a TenantQuota
                # edit or falling usage re-admits via the backlog.
                self.sched_recorder.warning(
                    pod, REASON_FAILED_SCHEDULING, veto)
                return "unschedulable"
        allocated_nodes = {
            c.allocation.node_name for c in claims.values()
            if c.allocation is not None and c.allocation.node_name
        }
        if len(allocated_nodes) > 1:
            msg = f"claims allocated on different nodes: {allocated_nodes}"
            self.sched_recorder.warning(pod, REASON_FAILED_SCHEDULING, msg)
            self._fail_pod(pod, msg)
            return "failed"
        if pod.node_name and allocated_nodes and pod.node_name not in allocated_nodes:
            # A nodeName-pinned pod whose shared claim is already
            # allocated elsewhere can never be prepared there.
            msg = (f"pod pinned to {pod.node_name} but claim allocated on "
                   f"{next(iter(allocated_nodes))}")
            self.sched_recorder.warning(pod, REASON_FAILED_SCHEDULING, msg)
            self._fail_pod(pod, msg)
            return "failed"
        if pod.node_name:
            candidates = [pod.node_name]
        elif allocated_nodes:
            # A shared, already-allocated claim pins the pod to its node.
            candidates = [next(iter(allocated_nodes))]
        else:
            candidates = None  # chosen per-claim-set below
        chosen = pod.node_name
        feasible_note = ""
        if unallocated:
            reject_reasons: Dict[str, str] = {}
            adm = self._admission
            shape = adm.shape_of(unallocated) if adm is not None else None
            pinned = candidates is not None
            cached = False
            if not pinned:
                # Feasibility pre-filter: only nodes that can possibly
                # satisfy every unallocated claim, in packing-aware
                # order (tightest-fit first for partial-node claim sets,
                # emptiest-first for whole-node/domain ones). The whole
                # dirty batch shares ONE computation per claim shape:
                # capacity only shrinks mid-pass, so the cached list is a
                # superset pruned as probes fail, and a pod only parks
                # after a fresh recompute confirms (below).
                feasible = adm.feasible.get(shape) if adm is not None else None
                if feasible is not None:
                    cached = True
                    self.allocator.note_feasible_cached(len(feasible))
                    candidates = [n for n in feasible if n in self.nodes
                                  and n not in self._down_nodes]
                    feasible_note = (f"feasibility filter admitted "
                                     f"{len(candidates)}/{len(self.nodes)} nodes")
                    candidates = self._steer_domain_candidates(
                        pod, unallocated, candidates, reject_reasons)
                else:
                    got = self._fresh_candidates(
                        pod, unallocated, shape, reject_reasons)
                    if got is None:
                        return "failed"
                    candidates, feasible_note = got
            prune_shape = shape if (adm is not None and not pinned) else None
            status, chosen_node = self._try_place_on(
                pod, unallocated, candidates, reject_reasons, prune_shape)
            if status == "failed":
                return "failed"
            if status == "noplace" and cached:
                # The shared snapshot said feasible but every probe failed:
                # the cache may simply be stale (siblings consumed the
                # capacity this pass). Recompute fresh — with full per-node
                # reasons — and give the pod one authoritative retry
                # before parking it.
                reject_reasons.clear()
                got = self._fresh_candidates(
                    pod, unallocated, shape, reject_reasons)
                if got is None:
                    return "failed"
                candidates, feasible_note = got
                status, chosen_node = self._try_place_on(
                    pod, unallocated, candidates, reject_reasons, shape)
                if status == "failed":
                    return "failed"
            if status == "noplace":
                log.debug("pod %s: unschedulable this pass", pod.key)
                self._record_unschedulable(pod, unallocated, reject_reasons)
                return "unschedulable"
            chosen = chosen_node
        if not chosen:
            if candidates is None:
                # No claims and no pin (a plain pod): any live node will do.
                candidates = sorted(n for n in self.nodes
                                    if n not in self._down_nodes)
            if not candidates:
                # Nowhere to put it (no nodes yet): park it so a NODE
                # event retries, instead of dropping it as 'bound'.
                return "unschedulable"
            chosen = candidates[0]
        if pod.node_name != chosen:
            # A pod carrying a propagated trace context (stamped by the
            # global scheduler when a placement/spill routed it here)
            # binds under that fleet-level trace, so cross-cluster
            # explain stitches the spill -> bind chain on one trace id.
            with tracing.span(
                    "scheduler.bind",
                    parent=tracing.extract_context(pod.meta.annotations),
                    pod=pod.key, node=chosen,
                    claim_uids=[c.uid for c in claims.values()]):
                def bind(obj, chosen=chosen):
                    obj.node_name = chosen
                try:
                    self.api.update_with_retry(POD, pod.meta.name, pod.namespace, bind)
                except NotFoundError:
                    return "bound"
                self.sched_recorder.normal(
                    pod, REASON_SCHEDULED,
                    f"assigned {pod.key} to {chosen}"
                    + (f" ({feasible_note})" if feasible_note else ""))
                self.history.decide(
                    controller="scheduler", rule=RULE_SCHED_BIND,
                    outcome="bound", obj=pod,
                    message=f"assigned to {chosen}",
                    inputs={"node": chosen,
                            "claims": sorted(c.meta.name
                                             for c in claims.values()),
                            "feasibility": feasible_note},
                    now=self.sim_time)
        # Every consumer of a claim is recorded (shared claims have
        # several); unprepare only happens when the last one is gone.
        from k8s_dra_driver_tpu.k8s.core import ResourceClaimConsumer

        for c in claims.values():
            if any(r.uid == pod.uid for r in c.reserved_for):
                continue  # already reserved: skip the no-op write

            def reserve(obj, pod=pod):
                if not any(r.uid == pod.uid for r in obj.reserved_for):
                    obj.reserved_for.append(ResourceClaimConsumer(
                        kind=POD, name=pod.meta.name, uid=pod.uid,
                    ))
            try:
                self.api.update_with_retry(
                    RESOURCE_CLAIM, c.meta.name, c.namespace, reserve
                )
            except NotFoundError:
                pass
        if self.contention is not None and unallocated:
            from k8s_dra_driver_tpu.scheduling.tiers import claim_chip_cost

            self.contention.charge(pod, sum(
                claim_chip_cost(c, self._host_chips) for c in unallocated))
        return "bound"

    def _fresh_candidates(self, pod: Pod, unallocated, shape: Optional[tuple],
                          reject_reasons: Dict[str, str]):
        """One authoritative feasibility computation for a pod: run the
        allocator pre-filter (storing the result into the pass admission
        cache), apply the node filter, and steer multi-host ComputeDomain
        workers onto their host-grid block. Returns (candidates, note),
        or None after failing the pod visibly (malformed class/selector).
        Both admission paths — first look and the stale-cache retry —
        go through here so they can never drift."""
        try:
            feasible = self.allocator.feasible_nodes(
                unallocated, reasons=reject_reasons)
        except AllocationError as e:
            msg = f"allocation: {e}"
            self.sched_recorder.warning(pod, REASON_FAILED_SCHEDULING, msg)
            self._fail_pod(pod, msg)
            return None
        adm = self._admission
        if adm is not None and shape is not None:
            adm.feasible[shape] = feasible
        candidates = [n for n in feasible if n in self.nodes
                      and n not in self._down_nodes]
        note = (f"feasibility filter admitted "
                f"{len(candidates)}/{len(self.nodes)} nodes")
        # Multi-host ComputeDomain workers: steer onto the domain's
        # host-grid-aligned block so the assembled clique is
        # ICI-contiguous, not just "N free hosts".
        candidates = self._steer_domain_candidates(
            pod, unallocated, candidates, reject_reasons)
        return candidates, note

    def _try_place_on(self, pod: Pod, unallocated, candidates,
                      reject_reasons: Dict[str, str],
                      prune_shape: Optional[tuple]):
        """Probe candidates in order and write the winning allocation.
        Returns ('placed', node), ('failed', None) — the pod was failed
        visibly — or ('noplace', None). With ``prune_shape``, a node whose
        probe fails is dropped from the admission snapshot's cached list
        so later same-shape pods of this pass skip it."""
        adm = self._admission
        for node in candidates:
            results = []
            ok = True
            for c in unallocated:
                # Sibling claims computed this pass count as
                # consumed, or two claims of one pod double-book.
                try:
                    r = self.allocator.allocate_on_node(
                        c, node, in_flight=[r for _, r in results])
                except AllocationError as e:
                    # A malformed class/selector must fail THIS
                    # pod visibly, not abort the scheduler pass
                    # for every other pod.
                    msg = f"allocation: {e}"
                    self.sched_recorder.warning(pod, REASON_FAILED_SCHEDULING, msg)
                    self._fail_pod(pod, msg)
                    return "failed", None
                if r is None:
                    ok = False
                    reject_reasons.setdefault(
                        node, f"claim {c.meta.name!r} does not fit "
                        "jointly with its siblings")
                    if adm is not None and prune_shape is not None:
                        adm.prune(prune_shape, node)
                    break
                results.append((c, r))
            if ok:
                for c, r in results:
                    # Consumers are recorded by the reserve loop in
                    # _schedule_pod; allocation only here.
                    def set_alloc(obj, r=r, node=node):
                        obj.allocation = r
                        set_condition(obj.conditions, CLAIM_COND_ALLOCATED,
                                      CONDITION_TRUE, "Allocated",
                                      f"allocated on {node}")
                    self.api.update_with_retry(
                        RESOURCE_CLAIM, c.meta.name, c.namespace, set_alloc
                    )
                    self.allocator.commit(r)
                return "placed", node
        return "noplace", None

    def _domain_by_uid(self, uid: str, namespace: Optional[str] = None):
        """ComputeDomain-by-uid lookup: the pass admission cache when a
        scheduler pass is active (a gang of workers resolves its domain
        once), a linear listing otherwise (domains are few)."""
        if not uid:
            return None
        adm = self._admission
        if adm is not None and uid in adm.domains:
            return adm.domains[uid]
        domains = (self.api.list(COMPUTE_DOMAIN, namespace=namespace)
                   if namespace else self.api.list(COMPUTE_DOMAIN))
        for cd in domains:
            if cd.uid == uid:
                if adm is not None:
                    adm.domains[uid] = cd
                return cd
        return None

    def _pod_compute_domain(self, claims):
        """The ComputeDomain a pod's claim set belongs to (via the channel
        claim's opaque ComputeDomainChannelConfig), or None."""
        for c in claims:
            uid = channel_domain_uid(c)
            if uid:
                return self._domain_by_uid(uid)
        return None

    def _steer_domain_candidates(self, pod: Pod, unallocated,
                                 candidates: List[str],
                                 reject_reasons: Optional[Dict[str, str]]
                                 = None) -> List[str]:
        """Host-grid-aligned domain placement. For a pod whose claims
        carry a ComputeDomain channel, prefer the domain's recorded
        host-grid block; when none is recorded yet, choose the most
        compact contiguous block of feasible hosts within one ICI domain
        (pkg.placement.choose_host_block) and record it in
        ComputeDomainStatus.

        When the cluster publishes host-grid coordinates but holds NO
        contiguous free block of the requested size, the workers park as
        unschedulable (empty candidate list) instead of degrading to
        scattered hosts: an unaligned "domain" spans several ICI meshes,
        can never assemble its clique, and strands whole hosts while it
        waits — exactly the fragmentation signal the live-repack
        rebalancer consumes to free a block. Clusters without host-grid
        attributes (no topology published) keep the legacy unaligned
        fallback. Once a block IS recorded, it is a preference — if its
        capacity got stolen, the remaining feasible nodes follow, so
        placement degrades instead of deadlocking."""
        if not candidates:
            return candidates
        cd = self._pod_compute_domain(unallocated)
        if cd is None or cd.spec.num_nodes <= 1:
            return candidates
        # Even a SINGLE feasible host must flow through the block check: a
        # multi-host domain worker binding unaligned to a lone free host
        # strands it (the channel claim pins the host against repack) and
        # the domain can never assemble there anyway.
        planned = cd.status.placement
        if planned is None:
            topologies = self.allocator.node_topologies()
            block = placement_lib.choose_host_block(
                topologies, candidates, cd.spec.num_nodes)
            if block is None:
                if not any(topologies.get(n, {}).get("host_coord")
                           is not None for n in candidates):
                    return candidates  # no grid info published: legacy path
                if reject_reasons is not None:
                    for n in candidates:
                        reject_reasons.setdefault(
                            n, f"free host outside any contiguous "
                            f"{cd.spec.num_nodes}-host grid block for "
                            f"ComputeDomain {cd.name} (fragmented: "
                            f"awaiting churn or live repack)")
                return []
            planned = ComputeDomainPlacement(
                ici_domain=block.ici_domain,
                block_origin=block.origin_str,
                block_shape=block.shape_str,
                nodes=list(block.nodes),
            )

            def set_placement(obj, planned=planned):
                if obj.status.placement is None:
                    obj.status.placement = planned
            try:
                updated = self.api.update_with_retry(
                    COMPUTE_DOMAIN, cd.name, cd.namespace, set_placement)
            except NotFoundError:
                return candidates
            if self._admission is not None:
                # The gang's later workers must see the recorded block,
                # not the stale pre-placement cache entry.
                self._admission.domains[updated.uid] = updated
            planned = updated.status.placement or planned
            self.sched_recorder.normal(
                cd, REASON_DOMAIN_PLACED,
                f"placed domain on host-grid block {planned.block_shape}"
                f"@{planned.block_origin} of ICI domain "
                f"{planned.ici_domain or '<default>'}: "
                + ",".join(planned.nodes))
        preferred = [n for n in planned.nodes if n in candidates]
        rest = [n for n in candidates if n not in preferred]
        return preferred + rest

    def _record_unschedulable(self, pod: Pod, unallocated, reasons) -> None:
        """FailedScheduling on the pod + AllocationFailed on each claim,
        carrying the feasibility filter's per-node verdicts — the
        `0/N nodes are available: ...` message kubectl users expect."""
        total = len(self.nodes)
        detail = "; ".join(
            f"{node}: {reason}" for node, reason in sorted(reasons.items())[:8]
        ) or "no candidate nodes"
        self.sched_recorder.warning(
            pod, REASON_FAILED_SCHEDULING,
            f"0/{total} nodes can place the pod: {detail}")
        self.history.decide(
            controller="scheduler", rule=RULE_SCHED_PARK,
            outcome="parked", obj=pod,
            message=f"0/{total} nodes can place the pod",
            inputs={"nodes": total,
                    "reject_reasons": dict(sorted(reasons.items())[:8]),
                    "claims": sorted(c.meta.name for c in unallocated)},
            now=self.sim_time)
        for c in unallocated:
            self.alloc_recorder.warning(
                c, REASON_ALLOCATION_FAILED,
                f"cannot allocate claim on any of {total} node(s): {detail}")

    # -- kubelet -------------------------------------------------------------------

    def _set_claim_condition(self, claim: ResourceClaim, type_: str,
                             status: str, reason: str, message: str) -> None:
        """Change-gated claim-condition write (a steady retry loop must not
        churn the claim's resourceVersion every pass). Gates on the LIVE
        object, not the pass's snapshot copy, so the second plugin of a
        two-driver pod doesn't re-write the condition the first just set."""
        live = self.api.try_get(RESOURCE_CLAIM, claim.meta.name, claim.namespace)
        if live is None:
            return
        cur = get_condition(live.conditions, type_)
        if (cur is not None and cur.status == status
                and cur.reason == reason and cur.message == message):
            return

        def mutate(obj):
            set_condition(obj.conditions, type_, status, reason, message)
        try:
            self.api.update_with_retry(
                RESOURCE_CLAIM, claim.meta.name, claim.namespace, mutate)
        except NotFoundError:
            pass

    def _kubelet_pass(self) -> None:
        self._drain_events()
        work, self._kubelet_dirty = self._kubelet_dirty, set()
        pending = sorted(work)
        try:
            while pending:
                key = pending.pop(0)
                pod = self.api.try_get(POD, key[1], key[0])
                if pod is None or not pod.node_name or pod.phase in ("Running", "Failed"):
                    continue
                if not self._kubelet_sync_pod(pod):
                    # Outstanding node-side work (retryable prepare, claims
                    # not yet allocated): stays dirty so the next pass
                    # retries even if no event touches this pod itself.
                    self._kubelet_dirty.add(key)
        except BaseException:
            # Same self-healing contract as the scheduler pass: a mid-pass
            # crash re-dirties everything not yet processed.
            self._kubelet_dirty.add(key)
            self._kubelet_dirty.update(pending)
            raise

    def _kubelet_sync_pod(self, pod: Pod) -> bool:
        """Run one kubelet sync for a bound pod; True when the pod reached
        a terminal phase (Running/Failed) and needs no more kubelet work."""
        node = self.nodes.get(pod.node_name)
        if node is None or pod.node_name in self._down_nodes:
            return False  # no kubelet answering on a failed host
        try:
            claims = self._ensure_claims_for_pod(pod)
        except AllocationError:
            return False
        if any(c.allocation is None for c in claims.values()):
            return False
        env: Dict[str, str] = {}
        devices: List[str] = []
        outcome = "ready"
        for claim in claims.values():
            for driver_name in sorted({r.driver for r in claim.allocation.devices}):
                plugin = (
                    node.tpu_driver if driver_name == TPU_DRIVER_NAME
                    else node.cd_driver
                )
                res = plugin.prepare_resource_claims([claim])[claim.uid]
                if isinstance(res, RetryableError):
                    outcome = "retry"  # pod stays ContainerCreating
                    self._set_claim_condition(
                        claim, CLAIM_COND_PREPARED, CONDITION_FALSE,
                        "Retrying", str(res))
                elif isinstance(res, Exception):
                    self._set_claim_condition(
                        claim, CLAIM_COND_PREPARED, CONDITION_FALSE,
                        "PrepareFailed", str(res))
                    self._fail_pod(pod, str(res))
                    outcome = "failed"
                    break
                else:
                    self._set_claim_condition(
                        claim, CLAIM_COND_PREPARED, CONDITION_TRUE,
                        "Prepared", f"prepared on {pod.node_name}")
                    cdi = plugin.state.cdi if hasattr(plugin, "state") else plugin.cdi
                    spec = cdi.read_claim_spec(claim.uid)
                    for dev in (spec or {}).get("devices", []):
                        edits = dev.get("containerEdits", {})
                        for e in edits.get("env", []):
                            k, _, v = e.partition("=")
                            if k in UNION_ENV_KEYS and env.get(k) and v:
                                # A pod holding several claims sees the
                                # union of their chip lists, like its
                                # device nodes (scalar env is CDI
                                # last-wins).
                                merged = set(env[k].split(",")) | set(v.split(","))
                                env[k] = ",".join(
                                    sorted(merged, key=lambda s: (len(s), s)))
                            else:
                                env[k] = v
                        for dn in edits.get("deviceNodes", []):
                            devices.append(dn["path"])
            if outcome == "failed":
                break
        if outcome == "failed":
            return True
        if outcome != "ready":
            return False

        def run(obj, env=env, devices=devices):
            obj.phase = "Running"
            obj.ready = True
            obj.pod_ip = obj.pod_ip or f"10.1.{abs(hash(obj.meta.name)) % 250}.{abs(hash(obj.namespace)) % 250}"
            obj.injected_env = env
            obj.injected_devices = sorted(set(devices))
        try:
            self.api.update_with_retry(POD, pod.meta.name, pod.namespace, run)
        except NotFoundError:
            pass
        return True

    def _fail_pod(self, pod: Pod, message: str) -> None:
        def mutate(obj, message=message):
            obj.phase = "Failed"
            obj.ready = False
            obj.meta.annotations["failure"] = message[:400]
        try:
            self.api.update_with_retry(POD, pod.meta.name, pod.namespace, mutate)
        except NotFoundError:
            pass

    # -- slice-agent pods ------------------------------------------------------------

    def _agent_pass(self) -> None:
        """Run/stop SliceAgents for slice-agent pods — the 'container' the
        DaemonSet started. Pod discovery is event-gated; the per-agent
        sync loop runs every step (clique convergence is driven by the
        agents themselves, not by API churn)."""
        self._drain_events()
        for (node_name, pod_name), pod in list(self._agent_pods.items()):
            node = self.nodes.get(node_name)
            if node is None or node_name in self._down_nodes:
                continue  # no kubelet to start containers on a dead host
            existing = node.agents.get(pod_name)
            if existing is not None:
                # Same name but a different pod uid means the old pod was
                # deleted and the DaemonSet recreated it within one step:
                # the agent container must actually restart.
                if getattr(existing, "_sim_pod_uid", None) == pod.uid:
                    continue
                existing.shutdown()
                del node.agents[pod_name]
            container = next(
                (c for c in pod.containers
                 if c.command and c.command[0] == "compute-domain-daemon"),
                None,
            )
            env = dict(container.env) if container else {}
            if container:
                # Kubelet materializes downward-API env from the pod.
                fields = {
                    "metadata.name": pod.meta.name,
                    "metadata.namespace": pod.namespace,
                    "status.podIP": pod.pod_ip,
                }
                for var, path in container.downward_env.items():
                    env[var] = fields.get(path, "")
            # A domain sized below its slice (numNodes < hosts, placed on
            # a host-grid sub-block) must assemble with numNodes members —
            # the whole-slice default would wait for hosts that never join.
            cd = self._domain_by_uid(
                env.get("COMPUTE_DOMAIN_UUID", ""),
                namespace=env.get("COMPUTE_DOMAIN_NAMESPACE", pod.namespace))
            expected_nodes = self._expected_members(cd)
            agent = SliceAgent(
                api=self.api,
                namespace=env.get("COMPUTE_DOMAIN_NAMESPACE", pod.namespace),
                domain_uid=env.get("COMPUTE_DOMAIN_UUID", ""),
                expected_nodes=expected_nodes,
                node_name=node_name,
                pod_ip=("127.0.0.1" if self.loopback_agents
                        else f"10.2.0.{len(node.agents) + 1}"),
                tpulib=node.tpulib,
                workdir=os.path.join(self.workdir, node_name, "agent", pod_name),
                gates=self.gates,
                pod_name=env.get("POD_NAME", ""),
                pod_namespace=env.get("POD_NAMESPACE", ""),
                metrics_registry=self.metrics_registry,
                # Liveness leases on the virtual clock: a killed agent's
                # lease expires a few steps later, deterministically.
                clock=lambda: self.sim_time,
                lease_duration_s=self.agent_lease_s,
            )
            agent.startup()
            agent._sim_pod_uid = pod.uid  # restart detection on DS recreate
            agent._sim_pod_ns = pod.namespace  # direct lookup in the sync loop
            node.agents[pod_name] = agent
        # Sync all agents; mark their pods ready per probe result.
        for node in self.nodes.values():
            if node.name in self._down_nodes:
                continue
            for pod_name, agent in list(node.agents.items()):
                ns = getattr(agent, "_sim_pod_ns", "default")
                pod = self.api.try_get(POD, pod_name, ns)
                if pod is not None and pod.node_name != node.name:
                    pod = None  # recreated on another node: not ours
                if pod is None:
                    agent.shutdown()
                    del node.agents[pod_name]
                    continue
                # Elastic membership: the expected member count follows
                # the LIVE placement (a healed 3-host domain must report
                # ready with 3 members, not wait for its dead fourth).
                cd = self._domain_by_uid(agent.domain_uid,
                                         namespace=agent.namespace)
                want = self._expected_members(cd)
                if want and agent.expected_nodes != want:
                    agent.expected_nodes = want
                agent.sync()
                ready = agent.check()
                if pod.ready == ready and pod.phase == "Running":
                    continue  # probe result unchanged: skip the no-op write

                def set_ready(obj, ready=ready):
                    obj.ready = ready
                    obj.phase = "Running"
                try:
                    self.api.update_with_retry(POD, pod.meta.name, pod.namespace, set_ready)
                except NotFoundError:
                    pass

    @staticmethod
    def _expected_members(cd) -> int:
        """How many clique members a domain's agents should wait for: the
        recorded placement's size once one exists (the resize orchestrator
        moves it), spec.numNodes before placement, 0 = follow the slice."""
        if cd is None:
            return 0
        if cd.status.placement is not None and cd.status.placement.nodes:
            return len(cd.status.placement.nodes)
        return cd.spec.num_nodes

    def _teardown_pod(self, pod: Pod) -> None:
        node = self.nodes.get(pod.node_name)
        if node and pod.meta.name in node.agents:
            node.agents[pod.meta.name].shutdown()
            del node.agents[pod.meta.name]

    # -- API-observed garbage collection -------------------------------------------

    def _gc_pass(self, force: bool = False) -> None:
        """React to deletions observed through the API — the path a kubectl
        delete takes on a real cluster: the garbage collector removes
        generated claims whose owner pod is gone (ownerRef GC), the
        resource-claim controller drops consumers of deleted pods, and the
        kubelet unprepares claims that no longer have any consumer or whose
        claim object vanished (the plugins' stale-claim cleanup,
        reference cleanup.go:149-259, runs the same sweep on a timer).

        Event-gated: runs only when a pod/DaemonSet/claim deletion or any
        claim movement was observed since the last run (``force=True`` for
        the direct delete_pod path, which bypasses the step loop)."""
        self._drain_events()
        if not (self._gc_dirty or force):
            return
        self._gc_dirty = False
        event_deleted, self._gc_deleted_claim_uids = (
            self._gc_deleted_claim_uids, set())
        ds_uids = {d.uid for d in self.api.list(DAEMON_SET)}
        for pod in self.api.list(POD):
            owner_ds = [r for r in pod.meta.owner_references if r.kind == DAEMON_SET]
            if owner_ds and all(r.uid not in ds_uids for r in owner_ds):
                self._teardown_pod(pod)
                try:
                    self.api.delete(POD, pod.meta.name, pod.namespace)
                except NotFoundError:
                    pass
        pod_uids = {p.uid for p in self.api.list(POD)}
        deleted_now: set = set(event_deleted)
        for claim in self.api.list(RESOURCE_CLAIM):
            owner_pods = [r for r in claim.meta.owner_references if r.kind == POD]
            if owner_pods and all(r.uid not in pod_uids for r in owner_pods):
                try:
                    self.api.delete(RESOURCE_CLAIM, claim.meta.name, claim.namespace)
                except NotFoundError:
                    pass
                deleted_now.add(claim.uid)
                continue
            if any(r.kind == POD and r.uid not in pod_uids
                   for r in claim.reserved_for):
                def drop(obj, pod_uids=pod_uids):
                    obj.reserved_for = [
                        r for r in obj.reserved_for
                        if not (r.kind == POD and r.uid not in pod_uids)
                    ]
                try:
                    self.api.update_with_retry(
                        RESOURCE_CLAIM, claim.meta.name, claim.namespace, drop
                    )
                except NotFoundError:
                    pass
        # The unprepare sweep reads every plugin checkpoint from disk, so
        # only run it when the API state suggests something to clean: a
        # claim uid vanished since the last pass (set-diff, plus DELETED
        # watch events covering claims that lived less than one gc run),
        # or an allocated claim lost its last consumer.
        live = {c.uid: c for c in self.api.list(RESOURCE_CLAIM)}
        vanished = (self._gc_prev_claim_uids - live.keys()) | deleted_now
        self._gc_prev_claim_uids = set(live.keys())
        unconsumed = any(
            c.allocation is not None and not c.reserved_for for c in live.values()
        )
        if not vanished and not unconsumed:
            return
        for node in self.nodes.values():
            if node.name in self._down_nodes:
                # A dead host runs no cleanup; its stale prepared state is
                # swept when the node returns (kubelet-restart semantics).
                continue
            for plugin in (node.tpu_driver, node.cd_driver):
                prepared = (
                    plugin.state.prepared_claims() if hasattr(plugin, "state")
                    else plugin.prepared_claims()
                )
                for uid, entry in prepared.items():
                    if getattr(entry, "state", "") == PREPARE_ABORTED:
                        continue  # tombstones expire on their own TTL
                    claim = live.get(uid)
                    if claim is not None and claim.reserved_for:
                        continue
                    plugin.unprepare_resource_claims([uid])

    # -- annotation-driven fault injection ------------------------------------------

    def _chaos_pass(self) -> None:
        """Apply CHAOS_CHIP_HEALTH_ANNOTATION deltas from Node objects to the
        mock tpulib, so external (kubectl-level) suites can drive the
        health -> taint -> republish chain (device_health.go:103-274).
        Event-gated on Node watch events — annotation edits arrive as
        MODIFIED; a quiet cluster skips the node listing entirely."""
        self._drain_events()
        if not self._chaos_dirty:
            return
        self._chaos_dirty = False
        returned: List[str] = []
        for node_obj in self.api.list(NODE):
            sim_node = self.nodes.get(node_obj.meta.name)
            if sim_node is None:
                continue
            value = node_obj.meta.annotations.get(CHAOS_CHIP_HEALTH_ANNOTATION, "")
            if value != self._chaos_applied.get(node_obj.meta.name, ""):
                for tok in filter(None, (t.strip() for t in value.split(","))):
                    idx, _, state = tok.partition("=")
                    try:
                        chip = int(idx)
                        health = ChipHealth(state.strip().lower())
                    except ValueError:
                        log.warning("chaos: bad chip health token %r on %s",
                                    tok, node_obj.meta.name)
                        continue
                    try:
                        sim_node.tpulib.set_health(chip, health)
                    except Exception:  # noqa: BLE001 — one bad chip must not drop the rest
                        log.exception("chaos: set_health(%d) failed on %s",
                                      chip, node_obj.meta.name)
                # Mark applied only after the whole annotation was processed
                # so a mid-loop crash retries the remaining tokens next pass.
                self._chaos_applied[node_obj.meta.name] = value
            link_value = node_obj.meta.annotations.get(
                CHAOS_LINK_HEALTH_ANNOTATION, "")
            if link_value != self._chaos_link_applied.get(node_obj.meta.name, ""):
                for tok in filter(None, (t.strip() for t in link_value.split(","))):
                    pair, _, state = tok.partition("=")
                    try:
                        a_s, _, b_s = pair.partition("-")
                        a, b = int(a_s), int(b_s)
                        health = ChipHealth(state.strip().lower())
                    except ValueError:
                        log.warning("chaos: bad link health token %r on %s",
                                    tok, node_obj.meta.name)
                        continue
                    try:
                        sim_node.tpulib.set_link_health(a, b, health)
                    except Exception:  # noqa: BLE001 — one bad link must not drop the rest
                        log.exception("chaos: set_link_health(%d,%d) failed on %s",
                                      a, b, node_obj.meta.name)
                self._chaos_link_applied[node_obj.meta.name] = link_value
            trace_value = node_obj.meta.annotations.get(
                CHAOS_LOAD_TRACE_ANNOTATION, "")
            if trace_value != self._chaos_trace_applied.get(node_obj.meta.name, ""):
                from k8s_dra_driver_tpu.tpulib.loadtrace import LoadTraceError

                try:
                    sim_node.tpulib.set_load_trace(trace_value or None)
                except LoadTraceError:
                    log.warning("chaos: bad load-trace spec %r on %s",
                                trace_value, node_obj.meta.name)
                self._chaos_trace_applied[node_obj.meta.name] = trace_value
            err_value = node_obj.meta.annotations.get(
                CHAOS_LINK_ERRORS_ANNOTATION, "")
            if err_value != self._chaos_link_err_applied.get(node_obj.meta.name, ""):
                for tok in filter(None, (t.strip() for t in err_value.split(","))):
                    pair, _, rate_s = tok.partition("=")
                    try:
                        a_s, _, b_s = pair.partition("-")
                        a, b = int(a_s), int(b_s)
                        rate = float(rate_s)
                    except ValueError:
                        log.warning("chaos: bad link-errors token %r on %s",
                                    tok, node_obj.meta.name)
                        continue
                    sim_node.tpulib.set_link_error_rate(a, b, rate)
                self._chaos_link_err_applied[node_obj.meta.name] = err_value
            down_value = node_obj.meta.annotations.get(
                CHAOS_NODE_DOWN_ANNOTATION, "")
            if down_value != self._chaos_down_applied.get(
                    node_obj.meta.name, ""):
                name = node_obj.meta.name
                if down_value.strip().lower() in ("true", "1"):
                    # Hard kill: agents die with NO API writes (leases
                    # stop renewing and expire — the failure signal); the
                    # node stops answering everywhere else via the
                    # _down_nodes membership checks.
                    self._down_nodes.add(name)
                    for agent in sim_node.agents.values():
                        agent.kill()
                    sim_node.agents.clear()
                else:
                    # Host returned: the agent pass restarts its agents
                    # from the (still-present) DaemonSet pods; stale
                    # prepared state from before the failure is swept
                    # below, kubelet-restart style.
                    self._down_nodes.discard(name)
                    returned.append(name)
                self._chaos_down_applied[name] = down_value
        if returned:
            # One listing for every returned node's stale sweep (a claim
            # deleted while the host was down must release its devices and
            # partitions now that the "kubelet" is back).
            live_uids = {c.uid for c in self.api.list(RESOURCE_CLAIM)}
            for name in returned:
                sim_node = self.nodes.get(name)
                if sim_node is None:
                    continue
                try:
                    sim_node.tpu_driver.cleanup_stale_claims()
                except Exception:  # noqa: BLE001 — sweep retried by the normal gc pass
                    log.exception("stale sweep on returned node %s failed",
                                  name)
                stale = [uid for uid, e
                         in sim_node.cd_driver.prepared_claims().items()
                         if uid not in live_uids
                         and e.state != PREPARE_ABORTED]
                if stale:
                    sim_node.cd_driver.unprepare_resource_claims(stale)
            self._gc_dirty = True

    # -- fleet telemetry ---------------------------------------------------------

    def _telemetry_pass(self) -> None:
        """One telemetry tick: advance the virtual clock, drive the
        serving traffic engine (its per-replica loads must land BEFORE
        sampling so this tick's counters reflect this tick's traffic),
        sample every node's monitor, roll samples up to claims/domains,
        evaluate the SLO rules, and run the autoscaler on the fresh
        alert snapshot. No-op unless the FleetTelemetry gate is on."""
        if self.telemetry is None:
            return
        self.telemetry_clock += self.telemetry_dt
        now = self.telemetry_clock
        serving_samples = None
        if self.serving is not None:
            serving_samples = self.serving.step(now, dt=self.telemetry_dt)
        views = []
        for name, node in self.nodes.items():
            node.tpu_driver.sample_telemetry(now=now)
            views.append(self.node_telemetry_view(name))
        self.telemetry.rollup(now, views)
        for (ns, cname), s in self.telemetry.claim_summaries().items():
            self.slo.observe(
                "claim-duty-cycle", now, s.duty_cycle_p95,
                subject=(ns, cname),
                ref=ObjectReference(kind=RESOURCE_CLAIM, name=cname,
                                    namespace=ns))
        for (ns, dname), s in self.telemetry.domain_summaries().items():
            self.slo.observe(
                "domain-ici-utilization", now, s.ici_utilization_p95,
                subject=(ns, dname),
                ref=ObjectReference(kind=COMPUTE_DOMAIN, name=dname,
                                    namespace=ns))
        self.slo.evaluate(now)
        if self.autoscaler is not None and serving_samples is not None:
            # Closed loop: scale on the snapshot the evaluation above
            # just refreshed; the resulting replica storm admits through
            # the scheduler's gang admission on the NEXT step.
            self.autoscaler.step(
                now, serving_samples,
                alerts=self.slo.active_alerts(),
                claim_summaries=self.telemetry.claim_summaries())

    def _observe_heal(self, trigger: str, elapsed: float, cd) -> None:
        """ElasticDomainController.heal_observer sink: completed resize
        epochs feed the time-to-healed burn-rate objective so a fleet
        that heals too slowly pages like any other SLO (the
        ``tpu_dra_resize_time_to_healed_seconds`` histogram remains the
        raw surface)."""
        from k8s_dra_driver_tpu.pkg.slo import TIME_TO_HEALED_SLO

        if self.slo is None:
            return
        self.slo.observe(
            TIME_TO_HEALED_SLO, self.telemetry_clock, elapsed,
            subject=(cd.namespace, cd.name),
            ref=ObjectReference(kind=COMPUTE_DOMAIN, name=cd.name,
                                namespace=cd.namespace, uid=cd.uid))

    def _fleet_free_chips(self) -> float:
        """Unallocated chips fleet-wide — the autoscaler's multi-group
        fairness hook compares the sum of desired scale-ups against this
        headroom before apportioning by tenant weight."""
        overview = self.allocator.placement_overview(TPU_DRIVER_NAME)
        free = 0
        for entry in overview.values():
            free += self._host_chips - placement_lib.popcount(
                entry["used_mask"])
        return float(max(0, free))

    def _install_claim_load(self, node_name: str, claim_uid: str,
                            duty: float) -> None:
        """TrafficEngine sink: per-replica duty into the node's mock
        tpulib workload registry (unknown nodes are skipped — the claim
        may be mid-migration)."""
        node = self.nodes.get(node_name)
        if node is not None:
            node.tpulib.set_workload_load(claim_uid, duty)

    def node_telemetry_view(self, name: str):
        """The aggregator's per-node input, built from in-memory monitor
        and checkpoint-mirror snapshots (zero store reads)."""
        from k8s_dra_driver_tpu.pkg.telemetry import ClaimChips, NodeView

        node = self.nodes[name]
        mon = node.tpu_driver.health
        stats = mon.window_stats()
        return NodeView(
            node=name,
            duty=stats.get("duty", {}),
            hbm_used=stats.get("hbm", {}),
            hbm_total=mon.hbm_totals(),
            link_util=mon.link_utilization(),
            claims=[
                ClaimChips(uid=uid, name=n, namespace=ns, chips=chips)
                for uid, (n, ns, chips)
                in node.tpu_driver.state.prepared_chipsets().items()
            ],
        )

    # -- pod-deletion driven unprepare -------------------------------------------------

    def delete_pod(self, name: str, namespace: str = "default") -> None:
        """Delete a pod kubelet-style: remove the object, then run the same
        API-observed GC the kubectl path relies on (consumer drop, ownerRef
        claim GC, unprepare of unconsumed claims)."""
        pod = self.api.try_get(POD, name, namespace)
        if pod is None:
            return
        self._teardown_pod(pod)
        try:
            self.api.delete(POD, name, namespace)
        except NotFoundError:
            pass
        self._gc_pass(force=True)
