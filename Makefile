# Build/test entry points (reference Makefile analog).

include versions.mk

.PHONY: all native test e2e bench bench-smoke ci clean version verify tpulint race check-metrics-docs check-event-reasons test-tier1

version:
	@echo "$(DRIVER_NAME) $(VERSION) (chart $(VERSION_NO_V), image $(IMAGE))"

# The full CI gate, exactly as .github/workflows declares it (add
# RUN_KIND=1 for the kind mock-cluster tier).
ci:
	hack/ci/run-local.sh

all: native test

native:
	cmake -S native -B native/build -G Ninja
	cmake --build native/build

test: native
	python -m pytest tests/ -x -q

e2e:
	python -m k8s_dra_driver_tpu.e2e

bench:
	python bench.py

# CI-sized bench pass: prepare-latency headline (20 iters) + batched
# prepare amortization + a 4-node scheduler storm + the 64-node indexed
# scheduler storm with a hard probes-per-bind budget assertion + the
# 2048-node scale-out gate (p99 claim-to-running budget, >=2x durable
# sharded-vs-single-lock write throughput with 8 writer threads, zero
# watch-ordering violations, fingerprint-identical WAL restore;
# BENCH_SCALE_NODES overrides the node count — full runs use 8192) +
# the 1024-node serving-autoscaler day (SLO violation minutes and
# wasted chip-hours vs the static baseline, zero burst flaps, zero
# steady-state store lists; BENCH_AUTOSCALER_NODES overrides) + the
# elastic-domain gate (ten seeded kill/heal cycles at 64 nodes: p99
# time-to-healed in virtual seconds, zero rollbacks, zero leaks) + the
# contention-plane gate (2048-node mixed-tenant churn storm: WFQ Jain
# fairness vs the FIFO baseline, per-tier p99 time-to-running with
# preemption strictly below no-preemption, zero half-assembled domains;
# BENCH_PREEMPT_NODES overrides) + the federation gate (1024-pod storm
# through the WAL replication stream: lag p99 within BENCH_FED_LAG_P99_MS
# with zero replica-side watch-ordering violations, fingerprint-token-
# identical convergence after a mid-storm partition heals, promote()
# serving writes after leader kill, >=BENCH_FED_OFFLOAD_MIN_X leader
# read-path reduction with lists routed to the follower, global placement
# p99 under BENCH_FED_PLACE_P99_MS). Capped at 30 min (the preempt A/B
# adds ~8.5 min at 2048 nodes).
bench-smoke:
	timeout -k 10 1800 env JAX_PLATFORMS=cpu python bench.py --smoke

# Pre-merge gate: the tpulint invariant analyzer (which subsumes the
# metrics-docs and event-reasons checks), the tpusan runtime concurrency
# sanitizer, plus the tier-1 pytest run (the suite ROADMAP.md pins as
# the regression floor).
verify: tpulint race test-tier1

# AST-based invariant analysis (k8s_dra_driver_tpu/analysis): CAS-closure
# purity, flock ordering, store-scan hygiene, k8s wire-drift, metric/event
# discipline, and doc sync — fails on any finding not in the committed
# baseline (hack/tpulint_baseline.json, empty: no legacy debt).
tpulint:
	python -m k8s_dra_driver_tpu.analysis

# tpusan — tpulint's runtime half (k8s_dra_driver_tpu/analysis/sanitizer):
# seeded-fixture self-test (every detector class must fire on every seed,
# naming both witness threads — including write-after-publish on the
# zero-copy store's freeze seam) + the control-plane concurrency
# scenarios driven by the interleaving explorer (must run clean). Run the
# whole pytest suite sanitized with `TPU_SAN=1 make test-tier1`.
race:
	env JAX_PLATFORMS=cpu python -m k8s_dra_driver_tpu.analysis.sanitizer --seeds 3

# Single-rule views of the tpulint engine (former standalone scripts).
check-metrics-docs:
	python hack/check_metrics_docs.py

check-event-reasons:
	python hack/check_event_reasons.py

test-tier1:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

clean:
	rm -rf native/build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
